"""The refit daemon: tap → fold → shadow-eval → publish → watch, forever.

One round (:meth:`RefitDaemon.run_once` — the deterministic, testable
unit the supervised loop repeats):

    tap.drain ──► split train/eval ──► fit_stream(state=…)  [refit.fold]
                        │                    │
                        │              export + persist state
                        ▼                    ▼
                  shadow.compare ◄──── candidate
                        │ fail → refit_skip (ledger) → done
                        │ pass
                        ▼
                  [refit.candidate] ──► publisher.publish  [refit.publish]
                        ▼
                  watch window: live score on held-back rows +
                  serving stats (failures, p99)
                        │ regression → publisher.rollback (ledger)
                        ▼
                  publisher.settle() — compile baseline restamped

The fold EXTENDS the persisted sufficient statistics (refit/state.py)
through the existing chunked ``fit_stream`` plan — the incremental cost
is O(new rows), never O(all rows ever seen), which is what the ``refit``
bench leg measures against a from-scratch fit. Rows absorbed into the
state stay absorbed even when a candidate is skipped or rolled back:
the DATA was real; it was the published MODEL that regressed.

Supervision: ``start()`` runs rounds on ``interval_s`` in a watched
daemon thread; a crashing round lands in the recovery ledger
(``refit_round_error``) and the loop keeps going until
``max_consecutive_failures`` rounds fail back to back
(``refit_daemon_failed``) — a poisoned feed must not spin forever.

Durability (docs/REFIT.md "Durable rounds"): with a store attached,
each round journals its drained rows + pre-fold state before folding
and advances the phase as it commits, so a kill anywhere mid-round
replays from the journal — exactly once — instead of losing rows the
tap no longer holds.

Chaos surface (docs/RELIABILITY.md): ``refit.fold`` faults the
incremental fold, ``refit.candidate`` intercepts the candidate AFTER
shadow eval and before publish (a ``corrupt`` spec here is the seeded
bad-candidate the auto-rollback e2e rolls back), ``refit.publish``
faults the swap itself.

The module also carries the synthetic drifting-workload closed loop
behind ``keystone-tpu refit`` (:func:`run_refit_demo`) — the chaos e2e
scripts/refit_smoke.sh gates in CI and the ``refit`` bench leg measures.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..envknobs import env_flag, env_float, env_int, env_str
from ..obs import names as _names
from ..obs import spans as _spans
from ..obs.flight import install_flight_recorder
from ..obs.quality import get_quality_plane
from ..reliability import faultinject
from ..reliability.faultinject import probe
from ..reliability.recovery import get_recovery_log
from .shadow import ShadowEvaluator
from .state import StreamState, load_stream_state, save_stream_state
from .tap import TrafficTap


@dataclass
class RefitConfig:
    """Knobs for one :class:`RefitDaemon` (env defaults via envknobs;
    the knob table lives in docs/REFIT.md)."""

    name: str = "default"
    #: seconds between supervised rounds (KEYSTONE_REFIT_INTERVAL_S).
    interval_s: float = field(
        default_factory=lambda: env_float("KEYSTONE_REFIT_INTERVAL_S", 30.0)
    )
    #: don't fold until this many labeled rows accumulated
    #: (KEYSTONE_REFIT_MIN_ROWS) — tiny folds are all overhead.
    min_rows: int = field(
        default_factory=lambda: env_int("KEYSTONE_REFIT_MIN_ROWS", 256)
    )
    #: cap per-round drain (bounds fold wall under a backlog).
    max_rows_per_round: int = 65536
    #: chunk rows for the incremental fold's chunk plan
    #: (KEYSTONE_REFIT_CHUNK_ROWS; one compiled shape → zero steady-state
    #: fold compiles after round 1).
    chunk_rows: int = field(
        default_factory=lambda: env_int("KEYSTONE_REFIT_CHUNK_ROWS", 1024)
    )
    #: freshest fraction of each drain held OUT of training for shadow
    #: eval + the post-publish watch window.
    eval_fraction: float = 0.25
    #: shadow gate: candidate passes at incumbent score - margin
    #: (KEYSTONE_REFIT_MARGIN).
    margin: float = field(
        default_factory=lambda: env_float("KEYSTONE_REFIT_MARGIN", 0.02)
    )
    #: watch gate: live score under incumbent - watch_margin rolls back
    #: (KEYSTONE_REFIT_WATCH_MARGIN).
    watch_margin: float = field(
        default_factory=lambda: env_float("KEYSTONE_REFIT_WATCH_MARGIN", 0.05)
    )
    #: watch rule (KEYSTONE_REFIT_WATCH_GATE): ``margin`` is the fixed
    #: floor above; ``sequential`` feeds per-row live-vs-incumbent scores
    #: into an anytime-valid mSPRT (obs/quality.py SequentialGate) and
    #: rolls back only on a statistically significant regression at
    #: ``gate_alpha`` — an undecided gate at window end promotes on
    #: budget, exactly like the fixed window expiring clean.
    watch_gate: str = field(
        default_factory=lambda: env_str("KEYSTONE_REFIT_WATCH_GATE", "margin")
    )
    #: sequential-watch false-positive bound (KEYSTONE_REFIT_GATE_ALPHA).
    gate_alpha: float = field(
        default_factory=lambda: env_float("KEYSTONE_REFIT_GATE_ALPHA", 0.05)
    )
    #: sequential-watch sample budget, capped at the watch rows available
    #: (KEYSTONE_REFIT_GATE_MAX_SAMPLES; candidate+baseline both count).
    gate_max_samples: int = field(
        default_factory=lambda: env_int(
            "KEYSTONE_REFIT_GATE_MAX_SAMPLES", 512
        )
    )
    #: let the quality plane's drift detector shrink ``state_decay``
    #: toward its floor under detected score drift, so the fold forgets
    #: the stale distribution faster (KEYSTONE_REFIT_ADAPTIVE_DECAY;
    #: docs/OBSERVABILITY.md "Quality plane").
    adaptive_decay: bool = field(
        default_factory=lambda: env_flag(
            "KEYSTONE_REFIT_ADAPTIVE_DECAY", False
        )
    )
    #: watch gate: post-publish serving p99 above this rolls back
    #: (None = score-only watch).
    watch_max_p99_ms: Optional[float] = None
    #: exponential forgetting applied to the stored statistics before
    #: each fold (KEYSTONE_REFIT_STATE_DECAY; 1.0 = remember everything
    #: equally — under drift a recency weight like 0.5 lets the model
    #: track the CURRENT distribution instead of the lifetime mixture).
    state_decay: float = field(
        default_factory=lambda: env_float("KEYSTONE_REFIT_STATE_DECAY", 1.0)
    )
    #: mirror rows handed to shadow eval per round.
    mirror_rows: int = 256
    #: supervised-loop restart budget: this many back-to-back failed
    #: rounds stops the daemon loudly.
    max_consecutive_failures: int = 5
    #: round-journal replay budget: a journaled batch that fails this
    #: many replays is DISCARDED (refit_journal_discard) — a poisoned
    #: drain must cost one batch, never wedge the daemon forever.
    max_journal_replays: int = 3
    #: persisted-state key in the checkpoint store.
    state_key: str = "refit-state"
    #: scheduled-path chunk policy: under a MeshScheduler, let the
    #: roofline placement (or a tuned ProfileStore entry) choose
    #: chunk_rows/prefetch instead of the static default
    #: (KEYSTONE_SCHED_AUTO_CHUNKS; docs/SCHEDULING.md "Pricing").
    auto_chunk_rows: bool = field(
        default_factory=lambda: env_flag("KEYSTONE_SCHED_AUTO_CHUNKS", False)
    )
    #: cursor cadence for SCHEDULED folds (chunks between commits): the
    #: preemption contract needs a committable cursor even on folds far
    #: below the durable auto-arm row threshold.
    sched_ckpt_every: int = field(
        default_factory=lambda: env_int("KEYSTONE_SCHED_CKPT_EVERY", 1)
    )


class RefitDaemon:
    """Supervised incremental-retrain loop over a traffic tap."""

    def __init__(
        self,
        estimator: Any,
        tap: TrafficTap,
        publisher: Any,
        store: Any = None,
        shadow: Optional[ShadowEvaluator] = None,
        config: Optional[RefitConfig] = None,
        partition: Any = None,
        state: Optional[StreamState] = None,
        scheduler: Any = None,
    ):
        self.estimator = estimator
        self.tap = tap
        self.publisher = publisher
        #: optional sched.MeshScheduler: rounds become cost-priced
        #: leases — admitted only into serving idle gaps, preempted at
        #: chunk boundaries under sustained SLO pressure (the deferred
        #: fold resumes from its durable cursor), and the sleep cadence
        #: turns backlog/pressure-driven (docs/SCHEDULING.md).
        self.scheduler = scheduler
        self._last_preempted_chunk: Optional[int] = None
        #: reliability CheckpointStore for the stream state (None = the
        #: state lives only in this process).
        self.store = store
        self.shadow = shadow or ShadowEvaluator()
        self.config = config or RefitConfig()
        if self.shadow.margin == 0.0:
            self.shadow.margin = self.config.margin
        #: optional PartitionDecision: the fold rides the sharded chunk
        #: plan exactly as a planned streamed fit would.
        self.partition = partition
        self._state: Optional[StreamState] = state
        if self._state is None and store is not None:
            self._state = load_stream_state(store, self.config.state_key)
        self._rounds = 0
        #: decay the last fold actually applied (== config.state_decay
        #: unless adaptive_decay let the drift detector shrink it).
        self.applied_decay: float = self.config.state_decay
        #: join token of the last watch window whose label join was
        #: persisted — a journal replay with a matching token skips the
        #: re-join (exactly-once across kills; docs/OBSERVABILITY.md
        #: "Quality plane").
        self._joined_token: Optional[str] = None
        if store is not None:
            from ..reliability.checkpoint import _MISS

            saved = store.lookup(None, digest=self._quality_state_key())
            if saved is not _MISS and isinstance(saved, dict):
                get_quality_plane().restore(
                    self.config.name, saved.get("state")
                )
                self._joined_token = saved.get("token")
        # Always-on flight recorder (idempotent): a watch-window
        # rollback's ledger event dumps this process's post-mortem.
        install_flight_recorder("refit")
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.outcomes: List[Dict[str, Any]] = []
        self._m_rounds = _names.metric(_names.REFIT_ROUNDS)
        self._m_state_rows = _names.metric(_names.REFIT_STATE_ROWS)
        self._m_fold_s = _names.metric(_names.REFIT_FOLD_SECONDS)
        self._m_score = _names.metric(_names.REFIT_SCORE)

    # ------------------------------------------------------------------ state
    @property
    def state(self) -> Optional[StreamState]:
        return self._state

    def state_rows(self) -> int:
        return int(self._state.num_examples) if self._state else 0

    # ------------------------------------------------------------------ round
    def run_once(self) -> str:
        """One refit round; returns the outcome
        (``published`` | ``skipped_nodata`` | ``skipped_eval`` |
        ``rolled_back``). Exceptions propagate — the supervised loop
        (not this method) owns the error ledger. Under an active trace
        session the whole round is one ``refit:round`` span tree —
        tap drain → fold → shadow → publish → watch share its trace id
        (docs/OBSERVABILITY.md "Fleet tracing")."""
        with self._lock:  # one fold at a time; state is read-modify-write
            with _spans.span(
                "refit:round", round=self._rounds + 1, daemon=self.config.name
            ) as round_span:
                outcome = self._run_once_locked()
                round_span.set_attribute("outcome", outcome)
                return outcome

    def _run_once_locked(self) -> str:
        self._rounds += 1
        round_index = self._rounds
        journal = self._load_journal()
        if journal is not None and journal.get("deferred"):
            # A scheduler preemption parked this batch mid-fold: a
            # deferral is a PLANNED resume, not a crash replay — the
            # attempts budget is untouched (satellite contract,
            # docs/SCHEDULING.md "Preemption"). Re-admission goes back
            # through the scheduler; still-pressured meshes keep the
            # batch parked (the journal and the durable cursor survive).
            lease = self._acquire_lease(
                round_index,
                rows=int(journal["x"].shape[0]),
                resume_of=journal.get("lease"),
            )
            if lease is not None and not lease.admitted:
                return self._outcome(
                    "deferred", round_index, keep_journal=True,
                    rows=int(journal["x"].shape[0]),
                    displaced_by=lease.displaced_by,
                )
            journal.pop("deferred", None)
            return self._resume_from_journal(journal, round_index, lease=lease)
        if journal is not None:
            # A previous round died mid-flight (kill between drain and
            # outcome). Its rows left the tap when they were drained —
            # the journal, not the tap, is where they survive. The
            # replay budget is persisted BEFORE the attempt (a crash
            # mid-replay counts), so a batch whose replay fails
            # deterministically is dropped after max_journal_replays
            # instead of wedging every future round (and every restarted
            # process) on the same poison.
            attempts = int(journal.get("attempts", 0)) + 1
            if attempts > self.config.max_journal_replays:
                self._clear_journal()
                get_recovery_log().record(
                    "refit_journal_discard",
                    self.config.name,
                    attempts=attempts - 1,
                    rows=int(journal["x"].shape[0]),
                    round=round_index,
                )
            else:
                journal["attempts"] = attempts
                self._save_journal(journal)
                return self._resume_from_journal(journal, round_index)
        depth = self.tap.depth()
        if depth < self.config.min_rows:
            get_recovery_log().record(
                "refit_skip",
                self.config.name,
                reason="insufficient_rows",
                rows=depth,
                min_rows=self.config.min_rows,
                round=round_index,
            )
            return self._outcome("skipped_nodata", round_index, rows=depth)

        # Admission BEFORE drain: a deferred fresh round costs nothing —
        # the rows stay in the tap (bounded, drop-oldest) and the
        # pressure-aware cadence retries sooner as it fills.
        lease = self._acquire_lease(
            round_index, rows=min(depth, self.config.max_rows_per_round)
        )
        if lease is not None and not lease.admitted:
            return self._outcome(
                "deferred", round_index, rows=depth,
                displaced_by=lease.displaced_by,
            )
        drained = self.tap.drain(self.config.max_rows_per_round)
        if drained is None:  # raced another drainer
            if lease is not None:
                self.scheduler.release(lease)
            return self._outcome("skipped_nodata", round_index, rows=0)
        x, y = drained
        return self._round_body(x, y, round_index, lease=lease)

    def _acquire_lease(
        self, round_index: int, rows: int, resume_of: Optional[str] = None
    ):
        """Price this round's fold and ask the scheduler for mesh time
        (None when unscheduled — the legacy path, byte for byte)."""
        if self.scheduler is None:
            return None
        from ..sched.scheduler import LeaseRequest

        width = classes = 0
        if self._state is not None:
            meta = getattr(self._state, "meta", {}) or {}
            width = int(meta.get("d", 0) or 0)
            classes = int(meta.get("k", 0) or 0)
        return self.scheduler.submit(
            LeaseRequest(
                name=f"{self.config.name}:round-{round_index}",
                kind="refit_fold",
                rows=int(rows),
                width=width,
                classes=classes,
                resume_of=resume_of,
            )
        )

    # -------------------------------------------------------- round journal
    #
    # Durable refit rounds (docs/REFIT.md, docs/RELIABILITY.md "Durable
    # fits"): the drained rows plus the PRE-fold state are journaled in
    # the checkpoint store before the fold runs, and the journal's phase
    # advances to "folded" only after the folded state is persisted — so
    # a SIGKILL anywhere inside a round replays it exactly once from the
    # journal instead of losing the drained rows, and a kill between the
    # state save and the phase advance rewinds to the pre-fold snapshot
    # (re-folding the same rows into already-extended statistics would
    # double-count them).

    def _journal_key(self) -> str:
        import hashlib

        return hashlib.sha1(
            f"keystone-refit-journal:{self.config.name}".encode()
        ).hexdigest()

    def _quality_state_key(self) -> str:
        import hashlib

        return hashlib.sha1(
            f"keystone-refit-quality:{self.config.name}".encode()
        ).hexdigest()

    def _save_journal(self, payload: Dict[str, Any]) -> None:
        if self.store is not None:
            self.store.save(None, payload, digest=self._journal_key())

    def _load_journal(self) -> Optional[Dict[str, Any]]:
        if self.store is None:
            return None
        from ..reliability.checkpoint import _MISS

        value = self.store.lookup(None, digest=self._journal_key())
        if value is _MISS or not isinstance(value, dict):
            return None
        return value if value.get("phase") in ("drained", "folded") else None

    def _clear_journal(self) -> None:
        if self.store is not None:
            self.store.delete(self._journal_key())

    def _resume_from_journal(
        self, journal: Dict[str, Any], round_index: int, lease: Any = None
    ) -> str:
        phase = str(journal.get("phase"))
        get_recovery_log().record(
            "refit_journal_resume",
            self.config.name,
            phase=phase,
            journaled_round=int(journal.get("round", 0)),
            round=round_index,
            rows=int(journal["x"].shape[0]),
        )
        _names.metric(_names.DURABLE_RESUMES).inc(kind="refit_journal")
        if phase == "drained":
            # The fold may have half-applied (or fully applied but died
            # before the phase advanced): rewind to the journaled
            # pre-fold snapshot so the re-fold is exactly once. For a
            # scheduler deferral the re-fold is still cheap: the durable
            # cursor (armed in _fold) holds the committed prefix and the
            # fold resumes mid-stream instead of from row zero.
            self._state = journal.get("state_before")
        return self._round_body(
            journal["x"], journal["y"], round_index,
            skip_fold=(phase == "folded"),
            attempts=int(journal.get("attempts", 0)),
            token=journal.get("token"),
            lease=lease,
        )

    def _round_body(
        self, x: np.ndarray, y: np.ndarray, round_index: int,
        skip_fold: bool = False, attempts: int = 0,
        token: Optional[str] = None, lease: Any = None,
    ) -> str:
        n = x.shape[0]
        eval_n = max(min(int(n * self.config.eval_fraction), n - 1), 1)
        train_x, train_y = x[: n - eval_n], y[: n - eval_n]
        eval_x, eval_y = x[n - eval_n :], y[n - eval_n :]

        # The journal commits BEFORE anything in the round can die: from
        # here on, a kill replays these rows from the store instead of
        # losing them with the drain.
        # attempts > 0 means this IS a journal replay: the store already
        # holds a byte-identical payload (saved with the bumped counter
        # moments ago), so only fresh rounds pay the drained-batch write.
        if not skip_fold and self.store is not None and attempts == 0:
            # The token identifies THIS drained batch across replays: the
            # quality-plane label join commits it with its state, so a
            # replayed batch whose join already persisted is not joined
            # twice (see _observe_quality).
            import os as _os

            token = _os.urandom(8).hex()
            self._save_journal(
                {
                    "phase": "drained",
                    "round": round_index,
                    "x": x,
                    "y": y,
                    "state_before": self._state,
                    "attempts": attempts,
                    "token": token,
                }
            )

        # ---------------------------------------------------- incremental fold
        preempted_at: Optional[int] = None
        with _spans.span("refit:fold", rows=int(train_x.shape[0])):
            probe("refit.fold")
            t_fold = time.perf_counter()
            if skip_fold:
                # Journal says the fold already committed: rebuild the
                # candidate from the persisted statistics alone.
                candidate = self.estimator.finish_from_state(self._state)
            else:
                candidate = self._fold(train_x, train_y, lease=lease)
                preempted_at = self._last_preempted_chunk
                if preempted_at is None:
                    self._state = self.estimator.export_stream_state()
                    if self.store is not None and self._state is not None:
                        save_stream_state(
                            self.store, self.config.state_key, self._state
                        )
                        self._save_journal(
                            {
                                "phase": "folded",
                                "round": round_index,
                                "x": x,
                                "y": y,
                                "attempts": attempts,
                                "token": token,
                            }
                        )
            fold_s = time.perf_counter() - t_fold
        if lease is not None:
            self.scheduler.release(lease)
        if preempted_at is not None:
            # Preempted at a chunk boundary under sustained SLO
            # pressure: the durable cursor holds the committed prefix.
            # Park the batch back in the journal as a PLANNED resume
            # (attempts untouched — not a crash) and leave self._state
            # at the pre-fold snapshot so nothing partial publishes.
            self._save_journal(
                {
                    "phase": "drained",
                    "round": round_index,
                    "x": x,
                    "y": y,
                    "state_before": self._state,
                    "attempts": attempts,
                    "token": token,
                    "deferred": True,
                    "lease": getattr(lease, "id", None),
                }
            )
            return self._outcome(
                "deferred", round_index, keep_journal=True,
                preempted_at_chunk=preempted_at, fold_s=fold_s,
                displaced_by=getattr(lease, "displaced_by", None),
            )
        self._m_fold_s.observe(fold_s)
        self._m_state_rows.set(self.state_rows())

        # -------------------------------------------------------- shadow eval
        incumbent = self.publisher.current_model()
        with _spans.span("refit:shadow", eval_rows=int(eval_n)):
            report = self.shadow.compare(
                candidate,
                incumbent,
                eval_x,
                eval_y,
                mirror_x=self.tap.mirror(self.config.mirror_rows),
            )
        if not report.passed:
            get_recovery_log().record(
                "refit_skip",
                self.config.name,
                reason="shadow_eval",
                round=round_index,
                **report.to_json(),
            )
            if hasattr(self.publisher, "settle"):
                self.publisher.settle()
            return self._outcome(
                "skipped_eval", round_index, fold_s=fold_s,
                shadow=report.to_json(),
            )

        # --------------------------------------------------- publish + watch
        injector = faultinject.current()
        if injector is not None:
            # The seeded-bad-candidate door: a `corrupt` spec at
            # refit.candidate lands AFTER shadow eval (an eval blind
            # spot is exactly how a bad candidate reaches traffic) and
            # the watch window below must catch it.
            candidate = injector.wrap("refit.candidate", lambda: candidate)()
        # The sequential watch needs the INCUMBENT's per-row scores on
        # the watch slice, and the incumbent stops being reachable the
        # moment the publish below swaps it out — score it here.
        incumbent_rows = None
        if self.config.watch_gate == "sequential":
            from .shadow import _predict

            try:
                incumbent_rows = self.shadow.score_rows(
                    _predict(incumbent, eval_x), eval_y
                )
            except Exception:
                incumbent_rows = None  # falls back to the margin rule
        with _spans.span("refit:publish", round=round_index):
            ticket = self.publisher.publish(candidate, round_index=round_index)
        outcome = self._watch(
            ticket, report, eval_x, eval_y, round_index,
            incumbent_rows=incumbent_rows, token=token,
        )
        if hasattr(self.publisher, "settle"):
            self.publisher.settle()
        return self._outcome(
            outcome, round_index, fold_s=fold_s, shadow=report.to_json(),
            version=ticket.version, state_decay=round(self.applied_decay, 4),
        )

    def _fold(self, train_x: np.ndarray, train_y: np.ndarray, lease: Any = None):
        """Fold new rows into the stored statistics through the existing
        chunked (optionally sharded) fit_stream plan.

        Under a scheduler lease the fold also becomes *preemptible*: a
        durable cursor (PR-15) is armed so every chunk boundary commits
        the fold prefix, and the lease's ``should_yield`` is consulted
        between chunks — sustained SLO pressure stops the fold at the
        boundary with the cursor intact (``self._last_preempted_chunk``
        carries the boundary out to ``_round_body``).
        """
        from ..data.dataset import ArrayDataset
        from ..workflow.streaming import ChunkStream

        self._last_preempted_chunk = None
        chunk_rows = self.config.chunk_rows
        if self.scheduler is not None and self.config.auto_chunk_rows:
            # Roofline-priced chunk geometry for the scheduled path: a
            # memory-bound fold wants larger chunks (fewer dispatch
            # boundaries per byte moved) up to the residency budget —
            # replacing the static default on this path only.
            chunk_rows, _prefetch, _src = self.scheduler.chunk_rows_for(
                rows=len(train_x),
                width=int(train_x.shape[1]),
                classes=int(train_y.shape[1]) if train_y.ndim > 1 else 1,
                default=self.config.chunk_rows,
            )
        stream = ChunkStream(
            ArrayDataset(train_x),
            ArrayDataset(train_y),
            (),
            chunk_rows=min(chunk_rows, max(len(train_x), 1)),
            partition=self.partition,
        )
        state = self._state
        decay = self.config.state_decay
        if self.config.adaptive_decay:
            # Quiet traffic keeps the configured decay; detected drift
            # shrinks it toward the detector's floor so the fold weights
            # the CURRENT distribution over the stale history.
            decay = get_quality_plane().suggested_decay(
                self.config.name, base=decay
            )
        self.applied_decay = decay

        durable = None
        if self.scheduler is not None and self.store is not None:
            # Preemption substrate: chunk-boundary checkpoints in the
            # SAME store the journal lives in. A valid cursor (resume
            # after deferral) already holds the decayed base plus the
            # committed prefix — seeding from it and skipping the decay
            # below is what keeps resume ≡ uninterrupted fold.
            from ..reliability.durable import arm_durable_fold

            durable, resume_state = arm_durable_fold(
                stream, self.estimator, self.store,
                ckpt_every=self.config.sched_ckpt_every,
            )
            if resume_state is not None:
                state = resume_state
                decay = 1.0
        if state is not None and decay < 1.0:
            state = state.scaled(decay)
        if durable is not None:
            # seed_rows AFTER decay: StreamState.scaled multiplies
            # num_examples too, and the cursor's row arithmetic is in
            # post-decay units.
            if durable.resume_rows == 0:
                durable.seed_rows = (
                    int(state.num_examples) if state is not None else 0
                )
            stream.durable = durable
            stream.lease = lease

        from ..workflow.streaming import last_stream_report

        result = self.estimator.fit_stream(stream, state=state)
        report = last_stream_report()
        if (
            lease is not None
            and report is not None
            and report.preempted_at_chunk is not None
        ):
            self._last_preempted_chunk = int(report.preempted_at_chunk)
        return result

    def _watch(
        self, ticket, shadow_report, watch_x, watch_y, round_index: int,
        incumbent_rows: Optional[np.ndarray] = None,
        token: Optional[str] = None,
    ) -> str:
        """Post-publish watch window, on its OWN thread: it scores live
        traffic, which is the shape a future non-blocking watch (running
        through the next round's tap accumulation) takes — today the
        round joins it before returning. The thread inherits the round's
        trace context via ``attach(current_context())``, so the
        ``refit:watch`` span nests under ``refit:round`` even though it
        runs on another thread (pinned by tests/refit/test_daemon.py)."""
        context = _spans.current_context()
        box: Dict[str, Any] = {}

        def watch_body() -> None:
            try:
                with _spans.attach(context), _spans.span(
                    "refit:watch", round=round_index,
                    version=str(ticket.version),
                ) as watch_span:
                    box["outcome"] = self._watch_inner(
                        ticket, shadow_report, watch_x, watch_y,
                        incumbent_rows=incumbent_rows, token=token,
                    )
                    watch_span.set_attribute("outcome", box["outcome"])
            except BaseException as exc:  # re-raised on the round thread
                box["error"] = exc

        thread = threading.Thread(
            target=watch_body, name="keystone-refit-watch"
        )
        thread.start()
        thread.join()
        if "error" in box:
            raise box["error"]
        return box["outcome"]

    def _watch_inner(
        self, ticket, shadow_report, watch_x, watch_y,
        incumbent_rows: Optional[np.ndarray] = None,
        token: Optional[str] = None,
    ) -> str:
        reason = None
        live_score = None
        live_rows = None
        try:
            live_pred = self.publisher.apply_live(watch_x)
            live_score = self.shadow.score_predictions(live_pred, watch_y)
            live_rows = self.shadow.score_rows(live_pred, watch_y)
            self._m_score.set(live_score, role="live")
        except Exception as exc:
            # The published version cannot even answer — that IS the
            # regression, not an excuse to skip the watch.
            reason = f"live apply failed: {type(exc).__name__}: {exc}"
        if live_rows is not None:
            self._observe_quality(live_rows, token)
        if (
            reason is None
            and self.config.watch_gate == "sequential"
            and live_rows is not None
            and incumbent_rows is not None
            and len(live_rows) >= 2
        ):
            reason = self._sequential_watch(live_rows, incumbent_rows)
        elif reason is None and live_score is not None:
            floor = shadow_report.incumbent_score - self.config.watch_margin
            if live_score < floor:
                reason = (
                    f"live score {live_score:.4f} < incumbent "
                    f"{shadow_report.incumbent_score:.4f} - "
                    f"{self.config.watch_margin}"
                )
        if reason is None and self.config.watch_max_p99_ms is not None:
            try:
                p99 = self.publisher.serving_stats().get("p99_ms")
            except Exception:
                p99 = None
            if isinstance(p99, (int, float)) and p99 > self.config.watch_max_p99_ms:
                reason = f"p99 {p99:.1f}ms > {self.config.watch_max_p99_ms}ms"
        if reason is None:
            return "published"
        self.publisher.rollback(ticket, reason=reason)
        return "rolled_back"

    # ------------------------------------------------------- quality plane
    #
    # The watch window's per-row live scores ARE the delayed-label join:
    # the rows carry labels (the tap's held-back slice), and scoring the
    # live serve path on them is exactly the "labeled accuracy stream"
    # the quality plane tracks (docs/OBSERVABILITY.md "Quality plane").
    # The join commits with the round — _persist_quality runs before the
    # journal clears, and a replayed batch whose token already persisted
    # is skipped — so a kill anywhere mid-round joins exactly once.

    def _observe_quality(
        self, live_rows: np.ndarray, token: Optional[str]
    ) -> None:
        if token is not None and token == self._joined_token:
            return  # replayed batch: this join already committed
        plane = get_quality_plane()
        model = self.config.name
        scores = [float(s) for s in live_rows]
        detector = plane.drift(model)
        for score in scores:
            plane.observe_score(model, score, role="live")
        if detector.baseline is None:
            # First watch window: adopt it as the drift reference.
            detector.freeze_baseline()
        else:
            plane.check_drift(model)
        plane.join_labels(model, scores)
        self._joined_token = token

    def _sequential_watch(
        self, live_rows: np.ndarray, incumbent_rows: np.ndarray
    ) -> Optional[str]:
        """Anytime-valid watch verdict: per-row live-vs-incumbent scores
        feed a quality-plane SequentialGate pairwise; the gate may stop
        the moment significance is reached. Returns the rollback reason,
        or None (promoted — by evidence or by exhausted budget)."""
        from ..obs.quality import quality_min_samples

        plane = get_quality_plane()
        budget = min(
            2 * min(len(live_rows), len(incumbent_rows)),
            self.config.gate_max_samples,
        )
        gate = plane.open_gate(
            self.config.name,
            kind="refit_watch",
            alpha=self.config.gate_alpha,
            min_samples=min(quality_min_samples(), budget),
            max_samples=budget,
        )
        verdict = "continue"
        for cand, base in zip(live_rows, incumbent_rows):
            verdict = gate.observe(candidate=float(cand), baseline=float(base))
            if verdict != "continue":
                break
        if verdict == "continue":
            # Window exhausted undecided: force the budget ruling so the
            # gate closes with archived evidence instead of lingering.
            gate.max_samples = min(gate.max_samples, gate.samples)
            verdict = gate.evaluate()
        evidence = plane.record_decision(gate)
        if verdict == "rollback":
            return (
                "sequential gate: live scores significantly below "
                f"incumbent (lr={evidence['lr']}, alpha={gate.alpha}, "
                f"samples={evidence['samples']})"
            )
        return None

    def _persist_quality(self) -> None:
        """Commit the quality plane's label-joined state (plus the join
        token) next to the stream state, atomically with round
        completion."""
        if self.store is None:
            return
        try:
            state = get_quality_plane().state(self.config.name)
            self.store.save(
                None,
                {"token": self._joined_token, "state": state},
                digest=self._quality_state_key(),
            )
        except Exception:
            pass  # quality is evidence, not correctness: never fail a round

    def _outcome(
        self, outcome: str, round_index: int,
        keep_journal: bool = False, **detail,
    ) -> str:
        # The round reached a decision: persist the quality join state,
        # then retire its journal (a no-op when none was written — skips
        # journal before the fold phase). A scheduler deferral KEEPS the
        # journal: it is the parked batch's survival, not a crash relic.
        self._persist_quality()
        if not keep_journal:
            self._clear_journal()
        # Join lag: labeled rows already in the tap that this round did
        # not reach — the backlog the next round's label join clears.
        _names.metric(_names.QUALITY_JOIN_LAG_ROWS).set(
            self.tap.depth(), model=self.config.name
        )
        self._m_rounds.inc(outcome=outcome)
        self.outcomes.append(
            {"round": round_index, "outcome": outcome, **detail}
        )
        return outcome

    # ------------------------------------------------------------ supervision
    def start(self) -> "RefitDaemon":
        """Run rounds every ``interval_s`` in a supervised daemon thread."""
        if self._thread is not None:
            raise RuntimeError("refit daemon already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="keystone-refit-daemon", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None

    def __enter__(self) -> "RefitDaemon":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _next_interval(self) -> float:
        """Pressure-aware cadence (docs/SCHEDULING.md): unscheduled
        daemons keep the fixed ``interval_s``; scheduled ones drain
        sooner as the tap fills toward its drop-oldest bound and back
        off while the mesh is under SLO pressure."""
        base = self.config.interval_s
        if self.scheduler is None:
            return base
        from ..sched.scheduler import pressure_aware_interval

        stats = self.tap.stats()
        fill = min(
            float(stats.get("labeled_depth", 0))
            / max(float(stats.get("capacity_rows", 1)), 1.0),
            1.0,
        )
        interval = pressure_aware_interval(
            base, fill, self.scheduler.pressure()
        )
        _names.metric(_names.SCHED_REFIT_INTERVAL_SECONDS).set(interval)
        return interval

    def _loop(self) -> None:
        failures = 0
        while not self._stop.wait(self._next_interval()):
            try:
                self.run_once()
                failures = 0
            except Exception as exc:
                failures += 1
                self._m_rounds.inc(outcome="error")
                get_recovery_log().record(
                    "refit_round_error",
                    self.config.name,
                    error=f"{type(exc).__name__}: {exc}",
                    consecutive=failures,
                )
                if failures >= self.config.max_consecutive_failures:
                    get_recovery_log().record(
                        "refit_daemon_failed",
                        self.config.name,
                        consecutive_failures=failures,
                    )
                    return


# ----------------------------------------------------------------- the demo
#
# A self-contained closed loop over a drifting synthetic classification
# workload: the CI face of the subsystem (scripts/refit_smoke.sh) and
# the `refit` bench leg's engine. Everything deterministic in --seed.


@dataclass
class RefitDemoConfig:
    d: int = 16
    classes: int = 4
    rounds: int = 6
    rows_per_round: int = 1024
    serve_requests: int = 192       # served per round (the live traffic)
    chunk_rows: int = 256
    drift: float = 0.2              # per-round weight perturbation scale
    state_decay: float = 0.5        # recency weight on the stored stats
    quiet_round: int = 2            # feeds too few rows → a ledgered skip
    bad_round: int = 4              # candidate corrupted → auto-rollback
    settle_round: int = 2           # steady-state compile assertions start
    seed: int = 0
    reg: float = 1e-2
    store_dir: Optional[str] = None
    watch_gate: str = "margin"      # or "sequential": anytime-valid watch
    adaptive_decay: bool = False    # drift detector steers state_decay


def _corrupt_mapper(model: Any) -> Any:
    """The seeded bad candidate: weights negated — shadow-eval-invisible
    by construction (it is injected AFTER eval) and catastrophically
    wrong on live traffic, which is the watch window's job to catch."""
    from ..ops.learning.linear import LinearMapper

    return LinearMapper(
        -np.asarray(model.weights),
        intercept=model.intercept,
        feature_mean=model.feature_mean,
    )


def run_refit_demo(config: RefitDemoConfig) -> Dict[str, Any]:
    """Drifting workload × continuous refit, end to end, in-process.

    Returns the evidence dict the smoke script and bench leg assert on:
    round outcomes, dropped-request and steady-state-compile counts,
    accuracy trajectory (live vs a stale never-refit incumbent), and the
    incremental-vs-scratch fold walls.
    """
    import tempfile

    from ..data.dataset import ArrayDataset
    from ..ops.learning.linear import LinearMapEstimator
    from ..reliability.checkpoint import CheckpointStore
    from ..serving.config import ServingConfig
    from ..serving.server import PipelineServer
    from ..workflow.streaming import ChunkStream
    from .publish import InProcessPublisher

    from ..obs.quality import reset_quality_plane

    cfg = config
    # The demo is an entry point: its quality evidence must reflect THIS
    # run, not whatever the process observed before.
    reset_quality_plane()
    rng = np.random.default_rng(cfg.seed)
    drift_rng = np.random.default_rng(cfg.seed + 1)

    w_true = rng.standard_normal((cfg.d, cfg.classes)).astype(np.float32)

    def drift_weights():
        nonlocal w_true
        step = drift_rng.standard_normal(w_true.shape).astype(np.float32)
        w = w_true + cfg.drift * step
        w_true = (w / np.linalg.norm(w, axis=0, keepdims=True)).astype(
            np.float32
        )

    def make_rows(n: int):
        x = rng.standard_normal((n, cfg.d)).astype(np.float32)
        labels = np.argmax(x @ w_true, axis=1)
        y = np.eye(cfg.classes, dtype=np.float32)[labels]
        return x, y, labels

    def stream_over(x, y):
        return ChunkStream(
            ArrayDataset(x), ArrayDataset(y), (),
            chunk_rows=min(cfg.chunk_rows, len(x)),
        )

    store_dir = cfg.store_dir or tempfile.mkdtemp(prefix="keystone-refit-")
    store = CheckpointStore(store_dir)

    # Incumbent v1: one streamed fit on pre-drift data, state captured.
    estimator = LinearMapEstimator(reg=cfg.reg)
    x0, y0, _ = make_rows(cfg.rows_per_round)
    v1_model = estimator.fit_stream(stream_over(x0, y0))
    save_stream_state(store, "refit-state", estimator.export_stream_state())

    tap = TrafficTap(capacity_rows=cfg.rows_per_round * 4, mirror_rows=512)
    server = PipelineServer(
        config=ServingConfig(max_batch=8, queue_depth=cfg.serve_requests + 64),
        name="demo",
        tap=tap,
    )
    server.registry.publish("demo", v1_model, source="fit")
    server.start()
    example = np.zeros((cfg.d,), np.float32)
    server.warmup(example)

    publisher = InProcessPublisher(server, name="demo", example=example)
    daemon = RefitDaemon(
        estimator,
        tap,
        publisher,
        store=store,
        # Margin well above one eval-row accuracy quantum (1/eval_rows):
        # under drift the incumbent and a one-round-fresher candidate
        # can score within a row or two of each other, and a gate at
        # that width would flip on compile-cache-level numeric jitter.
        shadow=ShadowEvaluator(margin=0.06),
        config=RefitConfig(
            name="demo",
            min_rows=max(cfg.rows_per_round // 2, 64),
            chunk_rows=cfg.chunk_rows,
            watch_margin=0.05,
            state_decay=cfg.state_decay,
            watch_gate=cfg.watch_gate,
            adaptive_decay=cfg.adaptive_decay,
        ),
        state=estimator.export_stream_state(),
    )

    rounds: List[Dict[str, Any]] = []
    dropped = 0
    steady_compiles = 0
    fold_walls: List[float] = []
    all_x, all_y = [x0], [y0]

    specs = []
    if cfg.bad_round:
        # The corrupt call number counts refit.candidate REACHES (rounds
        # that got past shadow eval), not wall-clock rounds; the quiet
        # round never reaches it.
        reaches = cfg.bad_round - (
            1 if cfg.quiet_round and cfg.quiet_round < cfg.bad_round else 0
        )
        specs.append(
            faultinject.FaultSpec(
                match="refit.candidate",
                kind="corrupt",
                calls=(reaches,),
                corrupt=_corrupt_mapper,
            )
        )

    import contextlib

    chaos = faultinject.injected(*specs) if specs else contextlib.nullcontext()
    try:
        with chaos:
            for r in range(1, cfg.rounds + 1):
                drift_weights()
                quiet = r == cfg.quiet_round
                n = 96 if quiet else cfg.rows_per_round
                x, y, labels = make_rows(n)

                # ---- live traffic through the serve path (zero drops).
                futures = server.submit_many(
                    [row for row in x[: cfg.serve_requests]],
                    deadline_s=120.0,
                )
                dropped += sum(
                    1 for f in futures if f.exception(timeout=180) is not None
                )
                stats = server.stats()
                if r > cfg.settle_round:
                    # Post-settle: serving between refit rounds must not
                    # compile (the publish re-warm + settle restamp own
                    # every legitimate compile).
                    steady_compiles = max(
                        steady_compiles,
                        int(stats.get("xla_compiles_since_warmup") or 0),
                    )

                # ---- labeled side-channel + one daemon round.
                tap.feed(x, y)
                all_x.append(x)
                all_y.append(y)
                t0 = time.perf_counter()
                outcome = daemon.run_once()
                round_wall = time.perf_counter() - t0
                fold_s = daemon.outcomes[-1].get("fold_s")
                if fold_s is not None:
                    # The drain+fold+finish wall alone — what the refit
                    # bench leg compares against a from-scratch fit.
                    fold_walls.append(fold_s)

                live_acc = _demo_accuracy(publisher, x, labels)
                # The accuracy probe above is demo instrumentation, not
                # serving traffic — restamp so next round's serving-only
                # window still reads zero compiles.
                server.restamp_compile_baseline()
                rounds.append(
                    {
                        "round": r,
                        "outcome": outcome,
                        "rows": n,
                        "live_accuracy": round(live_acc, 4),
                        "fold_s": round(fold_s, 4) if fold_s else None,
                        "round_wall_s": round(round_wall, 4),
                        "shadow": daemon.outcomes[-1].get("shadow"),
                    }
                )
    finally:
        server.stop(drain=True)

    # Evidence: stale v1 (never refit) vs the live, continuously-refit
    # line on the FINAL drifted distribution.
    final_x, _, final_labels = make_rows(2048)
    stale_acc = _model_accuracy(v1_model, final_x, final_labels)
    live_acc = _demo_accuracy(publisher, final_x, final_labels)

    # From-scratch comparison: one fit over every row the state absorbed.
    scratch_est = LinearMapEstimator(reg=cfg.reg)
    xs, ys = np.concatenate(all_x), np.concatenate(all_y)
    t0 = time.perf_counter()
    scratch_est.fit_stream(stream_over(xs, ys))
    scratch_wall = time.perf_counter() - t0
    incremental_wall = float(np.median(fold_walls)) if fold_walls else None

    outcomes = [r["outcome"] for r in rounds]
    ledger = get_recovery_log()
    # Quality-plane evidence: the labeled (watch-window) stream, drift
    # state, gate decisions, and the decay the last fold applied — the
    # bench `quality` obs block and REFIT_STATS consumers read this.
    quality_report = get_quality_plane().report()
    demo_view = quality_report["models"].get("demo", {})
    quality_block = {
        "label_joins": demo_view.get("label_joins", 0),
        "drift_score": demo_view.get("drift", {}).get("score", 0.0),
        "drift_events": demo_view.get("drift", {}).get("events", 0),
        "decisions": [d["decision"] for d in quality_report["decisions"]],
        # bench-diff exact-gates this count (deterministic seeded loop).
        "quality_decisions": len(quality_report["decisions"]),
        "join_lag_rows": tap.depth(),
        "state_decay_applied": round(daemon.applied_decay, 4),
        "labeled_mean": (
            demo_view.get("streams", {}).get("labeled", {}).get("mean")
        ),
    }
    return {
        "d": cfg.d,
        "classes": cfg.classes,
        "rounds": rounds,
        "publishes": outcomes.count("published"),
        "rollbacks": outcomes.count("rolled_back"),
        "skips": outcomes.count("skipped_nodata")
        + outcomes.count("skipped_eval"),
        "dropped": int(dropped),
        "compiles_steady_state_post_settle": int(steady_compiles),
        "state_rows": daemon.state_rows(),
        "tap": tap.stats(),
        "live_accuracy_final": round(live_acc, 4),
        "stale_v1_accuracy_final": round(stale_acc, 4),
        "incremental_refit_wall_s": (
            round(incremental_wall, 4) if incremental_wall else None
        ),
        "scratch_fit_wall_s": round(scratch_wall, 4),
        "refit_speedup": (
            round(scratch_wall / incremental_wall, 2)
            if incremental_wall
            else None
        ),
        "speedup_ok": bool(
            incremental_wall is not None and scratch_wall > incremental_wall
        ),
        "ledger_kinds": sorted(
            {e.kind for e in ledger.events() if e.kind.startswith("refit_")}
        ),
        "models": server.registry.describe(),
        "quality": quality_block,
    }


def _model_accuracy(model: Any, x: np.ndarray, labels: np.ndarray) -> float:
    from ..evaluation import MulticlassClassifierEvaluator

    scores = np.asarray(model.apply_arrays(x))
    k = scores.shape[1]
    return MulticlassClassifierEvaluator(k).evaluate(
        scores.argmax(axis=1), labels
    ).total_accuracy


def _demo_accuracy(publisher: Any, x: np.ndarray, labels: np.ndarray) -> float:
    from ..evaluation import MulticlassClassifierEvaluator

    scores = publisher.apply_live(x)
    k = scores.shape[1]
    return MulticlassClassifierEvaluator(k).evaluate(
        scores.argmax(axis=1), labels
    ).total_accuracy


# --------------------------------------------------------------------- CLI


def refit_from_args(args) -> int:
    """``keystone-tpu refit``: run the drifting-workload closed loop and
    print one ``REFIT_STATS:`` JSON line (the smoke-script contract)."""
    import json

    config = RefitDemoConfig(
        d=args.dim,
        classes=args.classes,
        rounds=args.rounds,
        rows_per_round=args.rows_per_round,
        serve_requests=args.serve_requests,
        chunk_rows=args.chunk_rows,
        drift=args.drift,
        quiet_round=args.quiet_round,
        bad_round=args.bad_round,
        seed=args.seed,
        store_dir=args.store_dir,
        watch_gate=getattr(args, "watch_gate", "margin"),
        adaptive_decay=bool(getattr(args, "adaptive_decay", False)),
    )
    results = run_refit_demo(config)
    results["recovery"] = get_recovery_log().summary()
    print("REFIT_STATS:" + json.dumps(results))
    return 0
