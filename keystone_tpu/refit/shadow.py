"""Shadow evaluation: score a refit candidate against the incumbent
before anything publishes.

Two signals, both computed WITHOUT touching the serve path:

- **Held-out score** — candidate and incumbent each applied to the
  freshest labeled rows the tap drained (rows the candidate did NOT
  train on this round), scored with the ``evaluation/`` suite:
  :class:`~keystone_tpu.evaluation.MulticlassClassifierEvaluator`
  accuracy when labels are classes (1-D ints or one-hot rows), negative
  mean-squared-error otherwise. Higher is always better.
- **Live mirror divergence** — candidate vs incumbent predictions on
  payloads sampled off real served traffic (the tap's mirror buffer):
  no labels needed, and a candidate that disagrees wildly with the
  incumbent on live inputs is flagged even when the held-out slice
  looks fine (distribution shift between the labeled feed and live
  traffic is exactly when that happens).

The gate: a candidate passes when its held-out score is at least the
incumbent's minus ``margin`` (drift means "no worse" is already a win —
the incumbent decays) AND the mirror divergence stays under
``max_mirror_divergence`` when a mirror set exists.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

from ..obs import names as _names


def _predict(model: Any, x: np.ndarray) -> np.ndarray:
    """Host predictions of a fitted model on a host matrix, via whatever
    apply door the model has (the ModelEntry.batch_apply normalization,
    minus the registry)."""
    from ..data.dataset import ArrayDataset

    dataset = ArrayDataset(np.asarray(x, np.float32))
    apply_batch = getattr(model, "apply_batch", None)
    if apply_batch is not None:
        out = apply_batch(dataset)
    else:
        out = model.batch_transform([dataset])
    data = getattr(out, "data", out)
    # Scoring is host-side by definition (the evaluator is numpy).
    # keystone: allow-sync
    return np.asarray(data)[: x.shape[0]]


def _as_classes(y: np.ndarray) -> Optional[np.ndarray]:
    """Labels as int classes when they are classes: 1-D integer-valued,
    or one-hot rows. None for genuine regression targets."""
    y = np.asarray(y)
    if y.ndim == 2 and y.shape[1] == 1:
        y = y[:, 0]
    if y.ndim == 1:
        if y.size and np.allclose(y, np.round(y)) and y.min() >= 0:
            return y.astype(np.int64)
        return None
    if y.ndim == 2 and y.shape[1] > 1:
        rows = y.sum(axis=1)
        if np.allclose(rows, 1.0) and np.allclose(y.max(axis=1), 1.0):
            return y.argmax(axis=1).astype(np.int64)
    return None


@dataclass
class ShadowReport:
    """One shadow comparison — what the ledger and metrics record."""

    candidate_score: float
    incumbent_score: float
    margin: float
    passed: bool
    metric: str = "accuracy"
    mirror_divergence: Optional[float] = None
    eval_rows: int = 0
    details: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        out = {
            "candidate_score": round(self.candidate_score, 6),
            "incumbent_score": round(self.incumbent_score, 6),
            "margin": self.margin,
            "passed": self.passed,
            "metric": self.metric,
            "eval_rows": self.eval_rows,
        }
        if self.mirror_divergence is not None:
            out["mirror_divergence"] = round(self.mirror_divergence, 6)
        return out


class ShadowEvaluator:
    """Score candidate vs incumbent on held-out labels + live mirror."""

    def __init__(
        self,
        margin: float = 0.0,
        max_mirror_divergence: Optional[float] = None,
        score_fn: Optional[Any] = None,
    ):
        #: candidate passes when score >= incumbent score - margin.
        self.margin = float(margin)
        #: mean relative prediction divergence on mirrored live traffic
        #: above this fails the candidate (None = mirror is advisory).
        self.max_mirror_divergence = max_mirror_divergence
        #: optional override: ``score_fn(predictions, labels) -> float``
        #: (higher better) replaces the built-in evaluation-suite scoring.
        self.score_fn = score_fn
        self._m_score = _names.metric(_names.REFIT_SCORE)

    # ----------------------------------------------------------------- scoring
    def score(self, model: Any, x: np.ndarray, y: np.ndarray) -> float:
        """One model's score on labeled rows — higher is better."""
        return self.score_predictions(_predict(model, x), y)

    def score_predictions(self, pred: np.ndarray, y: np.ndarray) -> float:
        """Score already-computed predictions (the watch window scores
        the LIVE serve path's outputs, not a model object)."""
        if self.score_fn is not None:
            return float(self.score_fn(pred, y))
        classes = _as_classes(y)
        if classes is not None:
            from ..evaluation import MulticlassClassifierEvaluator

            k = int(max(int(classes.max()) + 1, pred.shape[-1] if pred.ndim > 1 else 1))
            pred_classes = (
                pred.argmax(axis=1) if pred.ndim > 1 and pred.shape[1] > 1
                else np.round(pred).astype(np.int64).ravel().clip(0, k - 1)
            )
            return MulticlassClassifierEvaluator(k).evaluate(
                pred_classes, classes
            ).total_accuracy
        err = np.asarray(pred, np.float64) - np.asarray(y, np.float64)
        return -float(np.mean(err * err))  # negative MSE: higher is better

    def score_rows(
        self, pred: np.ndarray, y: np.ndarray
    ) -> Optional[np.ndarray]:
        """Per-row scores of already-computed predictions, higher better:
        0/1 correctness when labels are classes, negative squared error
        otherwise. The sequential watch gate and the quality plane's
        label-join stream consume these (a sample mean over them equals
        :meth:`score_predictions` for both metrics). ``None`` when a
        custom aggregate ``score_fn`` owns scoring — callers fall back
        to the aggregate margin rule then."""
        if self.score_fn is not None:
            return None
        pred = np.asarray(pred)
        classes = _as_classes(y)
        if classes is not None:
            k = int(max(
                int(classes.max()) + 1,
                pred.shape[-1] if pred.ndim > 1 else 1,
            ))
            pred_classes = (
                pred.argmax(axis=1) if pred.ndim > 1 and pred.shape[1] > 1
                else np.round(pred).astype(np.int64).ravel().clip(0, k - 1)
            )
            return (pred_classes == classes).astype(np.float64)
        err = np.asarray(pred, np.float64) - np.asarray(y, np.float64)
        if err.ndim > 1:
            return -np.mean(err * err, axis=tuple(range(1, err.ndim)))
        return -(err * err)

    def mirror_divergence(
        self, candidate: Any, incumbent: Any, mirror_x: np.ndarray
    ) -> float:
        """Mean relative L2 disagreement between candidate and incumbent
        predictions on live mirrored payloads."""
        a = np.asarray(_predict(candidate, mirror_x), np.float64)
        b = np.asarray(_predict(incumbent, mirror_x), np.float64)
        denom = max(float(np.linalg.norm(b)), 1e-12)
        return float(np.linalg.norm(a - b)) / denom

    # ----------------------------------------------------------------- verdict
    def compare(
        self,
        candidate: Any,
        incumbent: Any,
        eval_x: np.ndarray,
        eval_y: np.ndarray,
        mirror_x: Optional[np.ndarray] = None,
    ) -> ShadowReport:
        cand = self.score(candidate, eval_x, eval_y)
        inc = self.score(incumbent, eval_x, eval_y)
        metric = (
            "custom" if self.score_fn is not None
            else ("accuracy" if _as_classes(eval_y) is not None else "neg_mse")
        )
        divergence = None
        if mirror_x is not None and len(mirror_x):
            try:
                divergence = self.mirror_divergence(
                    candidate, incumbent, mirror_x
                )
            except Exception:
                divergence = None  # mirror is advisory; labels decide
        passed = cand >= inc - self.margin
        if (
            passed
            and divergence is not None
            and self.max_mirror_divergence is not None
            and divergence > self.max_mirror_divergence
        ):
            passed = False
        self._m_score.set(cand, role="candidate")
        self._m_score.set(inc, role="incumbent")
        return ShadowReport(
            candidate_score=cand,
            incumbent_score=inc,
            margin=self.margin,
            passed=passed,
            metric=metric,
            mirror_divergence=divergence,
            eval_rows=int(len(eval_x)),
        )
