"""Streaming row-space sketch operators: CountSketch and SRHT.

Both sketches compress the n-row (features, labels) stream into an
O(s·d) carry while staying exact under every composition the streaming
engine performs — chunking, row sharding, merge, exponential decay, and
crash-resume. The property that buys all of that at once: every row's
sketch contribution is a deterministic function of its ABSOLUTE dataset
row index (threaded through the engine's pad mask, which stores
``row_index + 1`` per row; see workflow/streaming.py), so the sketch of
a set of rows is the sum of per-row contributions no matter how the
rows were batched or which device folded them.

- **CountSketch** hashes row i to bucket h(i) ∈ [s] with sign σ(i) and
  scatter-adds σ(i)·xᵢ — O(n·d) stream flops, E[SᵀS] = I.
- **SRHT** uses the closed-form Walsh–Hadamard entry
  H(r, i) = (−1)^popcount(r & i) over the implicit 2³²-dimensional
  transform (``jax.lax.population_count``), sampled at s seeded rows r
  and sign-flipped per input row: each chunk contributes an (s, c)
  on-the-fly sign matrix times the chunk — O(s·c·d) flops, denser
  mixing than CountSketch for adversarial row distributions.

The carry is ``(SA, SY, s1, Σx, Σy)`` — sketched features (s, d),
sketched labels (s, k), the sketch of the all-ones vector (s,), and the
raw column sums. ``s1`` makes centering algebraic at finish time:
S·(A − 1μᵀ) = SA − s1·μᵀ, the same identity the Gram family uses, so
no second data pass is ever needed.

Row indices ride the float32 mask exactly up to 2²⁴ rows
(:data:`MASK_INDEX_EXACT_ROWS`); solvers refuse longer streams loudly.
"""

from __future__ import annotations

import functools

import numpy as np

#: Largest row count whose absolute indices are exactly representable in
#: the engine's float32 mask lane (2^24). Beyond this, index encoding
#: would silently collide — solvers raise instead of degrading.
MASK_INDEX_EXACT_ROWS = 1 << 24

#: Registered sketch variants (KEYSTONE_SKETCH_VARIANT values).
VARIANTS = ("countsketch", "srht")


def sketch_state_bytes(s: int, d: int, k: int) -> int:
    """Bytes one float32 sketch carry holds — the O(s·d) number the
    KV308 feasibility check compares against the device budget."""
    return 4 * (s * d + s * k + s + d + k)


# ------------------------------------------------------------- row hashing


def _avalanche(h):
    """murmur3 finalizer on uint32 lanes — full-entropy bit mixing, runs
    inside the fused chunk step (pure integer ops, no tables)."""
    import jax.numpy as jnp

    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _row_hash(idx_u32, seed: int, salt: int):
    """Deterministic uint32 hash of an absolute row index under
    (seed, salt) — the per-row randomness both variants draw from."""
    import jax.numpy as jnp

    mix = (int(seed) * 0x9E3779B9 + int(salt) * 0x7F4A7C15) & 0xFFFFFFFF
    return _avalanche(idx_u32 ^ jnp.uint32(mix))


def srht_sample_rows(s: int, seed: int) -> np.ndarray:
    """The s sampled Walsh–Hadamard row indices, host-generated and
    regenerable from (s, seed) alone — never persisted; resume rebuilds
    them from the envelope's meta."""
    rng = np.random.default_rng(np.uint64(seed) ^ np.uint64(0x5E1EC7ED))
    return rng.integers(0, 1 << 32, size=int(s), dtype=np.uint64).astype(
        np.uint32
    )


# ---------------------------------------------------------------- the carry


def sketch_stream_init(s: int, d: int, k: int):
    """Fresh float32 carry: (SA (s,d), SY (s,k), s1 (s,), Σx (d,),
    Σy (k,)) — every leaf additive over chunks AND shards, which is what
    lets kind="sketch" ride the engine's per-shard-partials path, the
    finish-time sum reduce, and shard-loss salvage unchanged."""
    import jax.numpy as jnp

    return (
        jnp.zeros((s, d), jnp.float32),
        jnp.zeros((s, k), jnp.float32),
        jnp.zeros((s,), jnp.float32),
        jnp.zeros((d,), jnp.float32),
        jnp.zeros((k,), jnp.float32),
    )


@functools.lru_cache(maxsize=32)
def sketch_stream_step(variant: str, seed: int):
    """The fold step for (variant, seed), memoized so repeated fits —
    refit rounds included — reuse ONE function object and therefore one
    entry in the engine's shared step-jit cache (0 steady compiles).

    The returned function carries ``needs_mask = True``: the engine then
    passes the chunk's pad mask, whose lane holds each row's absolute
    dataset index + 1 (0 for pads) — the only extra plumbing the sketch
    tier needed from the engine.
    """
    if variant not in VARIANTS:
        raise ValueError(
            f"unknown sketch variant {variant!r} (known: {VARIANTS})"
        )
    seed = int(seed)

    if variant == "countsketch":

        def _hash_rows(mask, s):
            import jax.numpy as jnp

            idx1 = mask[:, 0].astype(jnp.int32)  # row index + 1; 0 = pad
            valid = (idx1 > 0).astype(jnp.float32)
            idx = jnp.maximum(idx1 - 1, 0).astype(jnp.uint32)
            bucket = (_row_hash(idx, seed, 0) % jnp.uint32(s)).astype(
                jnp.int32
            )
            sign = (
                1.0 - 2.0 * (_row_hash(idx, seed, 1) & jnp.uint32(1)).astype(
                    jnp.float32
                )
            ) * valid
            return bucket, sign

        def step(carry, x, y, mask):
            import jax.numpy as jnp

            sa, sy, s1, sx, sums_y = carry
            bucket, sign = _hash_rows(mask, sa.shape[0])
            sa = sa.at[bucket].add(sign[:, None] * x)
            sy = sy.at[bucket].add(sign[:, None] * y)
            s1 = s1.at[bucket].add(sign)
            # Pads are exact zeros in x (the chain re-zeroes them) and y
            # (host pad), so raw column sums need no masking.
            return (
                sa, sy, s1,
                sx + jnp.sum(x, axis=0),
                sums_y + jnp.sum(y, axis=0),
            )

        def block_step(carry, x, y, mask, block_index):
            # Model-axis variant: this device holds the block_index-th
            # column block of SA/Σx — (s, d/p_model) — and scatter-adds
            # its own column slice of the chunk. SY/s1/Σy are feature-
            # free: block 0 owns them (finish SUMS non-feature leaves).
            from jax import lax
            import jax.numpy as jnp

            sa, sy, s1, sx, sums_y = carry
            b = sa.shape[1]  # static block width; block_index is traced
            bucket, sign = _hash_rows(mask, sa.shape[0])
            xb = lax.dynamic_slice_in_dim(x, block_index * b, b, axis=1)
            on0 = (block_index == 0).astype(jnp.float32)
            sa = sa.at[bucket].add(sign[:, None] * xb)
            sy = sy.at[bucket].add((on0 * sign)[:, None] * y)
            s1 = s1.at[bucket].add(on0 * sign)
            return (
                sa, sy, s1,
                sx + jnp.sum(xb, axis=0),
                sums_y + on0 * jnp.sum(y, axis=0),
            )

    else:  # srht

        def _mix_matrix(mask, s):
            import jax
            import jax.numpy as jnp

            idx1 = mask[:, 0].astype(jnp.int32)
            valid = (idx1 > 0).astype(jnp.float32)
            idx = jnp.maximum(idx1 - 1, 0).astype(jnp.uint32)
            rows = jnp.asarray(srht_sample_rows(s, seed))  # (s,) uint32
            # H(r, i) = (−1)^popcount(r & i): the Walsh–Hadamard entry in
            # closed form — row-independent, so sharding stays exact.
            parity = (
                jax.lax.population_count(rows[:, None] & idx[None, :])
                & jnp.uint32(1)
            ).astype(jnp.float32)
            sign = (
                1.0 - 2.0 * (_row_hash(idx, seed, 1) & jnp.uint32(1)).astype(
                    jnp.float32
                )
            ) * valid
            return (1.0 - 2.0 * parity) * sign[None, :] * (1.0 / np.sqrt(s))

        def step(carry, x, y, mask):
            import jax.numpy as jnp

            sa, sy, s1, sx, sums_y = carry
            m = _mix_matrix(mask, sa.shape[0])
            return (
                sa + m @ x,
                sy + m @ y,
                s1 + jnp.sum(m, axis=1),
                sx + jnp.sum(x, axis=0),
                sums_y + jnp.sum(y, axis=0),
            )

        def block_step(carry, x, y, mask, block_index):
            from jax import lax
            import jax.numpy as jnp

            sa, sy, s1, sx, sums_y = carry
            b = sa.shape[1]
            m = _mix_matrix(mask, sa.shape[0])
            xb = lax.dynamic_slice_in_dim(x, block_index * b, b, axis=1)
            on0 = (block_index == 0).astype(jnp.float32)
            return (
                sa + m @ xb,
                sy + on0 * (m @ y),
                s1 + on0 * jnp.sum(m, axis=1),
                sx + jnp.sum(xb, axis=0),
                sums_y + on0 * jnp.sum(y, axis=0),
            )

    step.needs_mask = True
    step.sketch_variant = variant
    step.sketch_seed = seed
    # Blocked-carry protocol (workflow/streaming.py 2-D layouts): the
    # feature axis of each carry leaf (SA cols, Σx) — None = feature-free.
    step.model_layout = (1, None, None, 0, None)
    step.model_block_step = block_step
    return step


def sketch_stream_finish(carry, n: int):
    """Centered sketches from the accumulated carry: S·Ac, S·Yc, and the
    means — S·(A − 1μᵀ) = SA − s1·μᵀ, exact for any sketch that is a
    linear map of the rows (both variants are)."""
    sa, sy, s1, sx, sums_y = carry
    mu_a = sx / n
    mu_b = sums_y / n
    sa_c = sa - s1[:, None] * mu_a[None, :]
    sy_c = sy - s1[:, None] * mu_b[None, :]
    return sa_c, sy_c, mu_a, mu_b


# ----------------------------------------------------------- in-core sketch


def sketch_rows(x, start_index: int, variant: str, seed: int, s: int):
    """Sketch a materialized row block whose rows occupy absolute
    indices [start_index, start_index + rows): the in-core counterpart
    of one stream chunk, sharing the exact per-row hashing — sketching
    a matrix block-by-block equals sketching it whole (the additivity
    the round-trip tests pin)."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    rows = x.shape[0]
    step = sketch_stream_step(variant, seed)
    mask = (
        jnp.arange(start_index + 1, start_index + rows + 1, dtype=jnp.float32)
    )[:, None]
    carry = (
        jnp.zeros((s, x.shape[1]), jnp.float32),
        jnp.zeros((s, 1), jnp.float32),
        jnp.zeros((s,), jnp.float32),
        jnp.zeros((x.shape[1],), jnp.float32),
        jnp.zeros((1,), jnp.float32),
    )
    sa, _, s1, _, _ = step(carry, x, jnp.zeros((rows, 1), jnp.float32), mask)
    return sa, s1
