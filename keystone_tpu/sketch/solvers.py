"""Sketch-based least-squares solvers and randomized Nyström KRR.

Two regimes share the operators in :mod:`.core`:

- **Streamed** (:meth:`SketchedLeastSquaresEstimator.fit_stream`): pure
  one-pass sketch-and-solve. The fold accumulates the O(s·d) carry; the
  finish solves the SKETCHED ridge objective exactly via the dual
  (push-through) identity ``(ÃᵀÃ+λI)⁻¹Ãᵀ = Ãᵀ(ÃÃᵀ+λI)⁻¹`` — an s×s
  solve, never a d×d one. Error vs the exact solution is the classic
  subspace-embedding bound: relative residual O(ε) when s = Θ(d/ε²)
  (docs/SOLVERS.md), and the estimator's default s keeps fits in the
  full-accuracy regime until width forces the trade.
- **In-core** (:meth:`SketchedLeastSquaresEstimator.fit` /
  :func:`sketch_precond_lstsq`): sketch-and-PRECONDITION. The same
  sketch builds a Woodbury preconditioner for block PCG on the full
  normal operator — a handful of refinement passes
  (``KEYSTONE_SKETCH_REFINE``) drive the error to solver tolerance
  while every iteration stays O(n·d·k).

The streamed carry is kind="sketch" :class:`~..refit.state.StreamState`
(every leaf additive), so merge/``scaled()``/crash-resume/shard-loss
salvage ride the PR-12/PR-15 contracts with zero new persistence code.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..envknobs import env_int, env_str
from ..refit.state import SketchStreamStateMixin
from ..workflow.pipeline import LabelEstimator
from .core import (
    MASK_INDEX_EXACT_ROWS,
    VARIANTS,
    sketch_state_bytes,
    sketch_stream_finish,
    sketch_stream_init,
    sketch_stream_step,
)


def default_sketch_size(d: int) -> int:
    """Sketch rows for a width-d fit when nothing pins one: ``min(4096,
    max(128, d))``. At s ≥ d the sketched ridge objective is a
    full-rank compression (near-exact recovery); only past d=4096 does
    the O(s·d) state force the accuracy/memory trade the error bounds
    in docs/SOLVERS.md quantify."""
    return int(min(4096, max(128, int(d))))


def sketch_min_width() -> int:
    """Ladder eligibility floor (``KEYSTONE_SKETCH_MIN_WIDTH``): below
    this featurized width the exact/Gram rungs are both affordable and
    more accurate, so the sketched rung prices itself out (inf)."""
    return env_int("KEYSTONE_SKETCH_MIN_WIDTH", 8192)


def _refine_iters_default() -> int:
    return env_int("KEYSTONE_SKETCH_REFINE", 16)


def _reg_floor(k_mat, s: int, reg: float) -> float:
    """λ for the s×s dual solve: the caller's ridge when set, else the
    scale-aware floor the block solver uses (block.py) — relative to
    tr(K)/s so a rank-deficient sketch factors finitely instead of
    emitting NaNs."""
    if reg and reg > 0:
        return float(reg)
    import jax.numpy as jnp

    return max(1e-6 * float(jnp.trace(k_mat)) / max(s, 1), 1e-6)


class SketchedLeastSquaresEstimator(SketchStreamStateMixin, LabelEstimator):
    """Least squares from an O(s·d) row-space sketch.

    The very-wide rung of the solver ladder (least_squares.py): state
    O(s·d) vs the Gram family's O(d²), so a d≥64k streamed fit holds in
    memory where KV303 refuses the Gram path. ``reg`` follows the exact
    rung's contract (>0 ridge, 0/None minimum-norm via the scale-aware
    floor); ``sketch_size``/``variant``/``seed`` default from the
    ``KEYSTONE_SKETCH_*`` knobs (docs/SOLVERS.md).
    """

    #: Chunked-fit protocol (workflow/streaming.py): the sketch carry
    #: accumulates per chunk exactly like a Gram does.
    supports_fit_stream = True

    #: 2-D partitioner protocol: SA/Σx shard the feature axis
    #: (sketch_stream_step's blocked protocol) on a (data, model) mesh.
    supports_model_axis = True

    def __init__(
        self,
        reg: Optional[float] = None,
        sketch_size: Optional[int] = None,
        variant: Optional[str] = None,
        seed: Optional[int] = None,
        refine_iters: Optional[int] = None,
    ):
        self.reg = reg
        self.sketch_size = sketch_size
        self.variant = variant or env_str(
            "KEYSTONE_SKETCH_VARIANT", "countsketch"
        )
        if self.variant not in VARIANTS:
            raise ValueError(
                f"KEYSTONE_SKETCH_VARIANT={self.variant!r} "
                f"(known: {VARIANTS})"
            )
        self.seed = (
            env_int("KEYSTONE_SKETCH_SEED", 0) if seed is None else int(seed)
        )
        self.refine_iters = refine_iters

    # ------------------------------------------------------- configuration
    def _resolve_sketch_size(self, d: int) -> int:
        """Priority: env knob > constructor > MeasuredKnobRule's tuned
        winner (knobs.py copies estimators with ``_tuned_sketch_size``)
        > width default."""
        s = env_int("KEYSTONE_SKETCH_SIZE", 0)
        if s > 0:
            return s
        if self.sketch_size:
            return int(self.sketch_size)
        tuned = getattr(self, "_tuned_sketch_size", 0)
        if tuned:
            return int(tuned)
        return default_sketch_size(d)

    @property
    def stream_state_meta(self):
        """Envelope meta for kind="sketch" states: what a resumed or
        merged fold must agree on for the additive carry algebra to be
        meaningful (sizes are structural — carried by the shapes)."""
        return {
            "sketch_variant": self.variant,
            "sketch_seed": int(self.seed),
        }

    def out_spec(self, in_specs):
        """Plan-time spec protocol (workflow/verify.py): same fitted-map
        shape as every least-squares rung."""
        from ..workflow.verify import dense_fit_spec

        return dense_fit_spec(in_specs, self.label)

    # ------------------------------------------------------- streamed path
    def fit_stream(self, stream, state=None):
        """One-pass sketch-and-solve over the chunk stream.

        ``state`` (kind="sketch") seeds the carry so the fold EXTENDS an
        earlier fit — resuming adopts the state's (variant, seed) so the
        combined sketch stays one coherent linear map of all rows."""
        from ..ops.learning.block import _stream_shapes
        from ..workflow.streaming import StreamingFallback

        n_rows = int(getattr(stream, "num_examples", 0))
        if n_rows > MASK_INDEX_EXACT_ROWS:
            raise StreamingFallback(
                f"sketch row indices exceed float32-exact range "
                f"({n_rows} > {MASK_INDEX_EXACT_ROWS})"
            )
        variant, seed = self.variant, self.seed
        if state is not None and state.meta.get("sketch_variant"):
            variant = state.meta["sketch_variant"]
            seed = int(state.meta.get("sketch_seed", seed))
            self.variant, self.seed = variant, seed
        shapes = {}

        def init(feat_aval, y_aval):
            d, k = _stream_shapes(feat_aval, y_aval)
            s = self._resolve_sketch_size(d)
            shapes.update(s=s, d=d, k=k)
            return self._seed_carry(state, s, d, k)

        t0 = time.perf_counter()
        carry, info = stream.fold(init, sketch_stream_step(variant, seed))
        n = info["num_examples"] + (state.num_examples if state else 0)
        self._capture_state(
            carry, n, reg=self.reg,
            sketch_variant=variant, sketch_seed=int(seed),
        )
        model = self._finish_from_stats(carry, n)
        self._observe(
            rows=n, wall_s=time.perf_counter() - t0, variant=variant, **shapes
        )
        return model

    def _finish_from_stats(self, carry, n: int):
        """Solve the sketched objective from the carry alone — shared by
        the streamed fit and the refit ``finish_from_state`` path.

        Rung 1 ("dual") is the s×s dual-ridge solve; when it OOMs the
        ladder degrades to a direct lstsq on the sketched system
        (O(s·d·min(s,d)) workspace instead of s² + the Cholesky's
        temporaries) — slower, never bigger."""
        import jax.numpy as jnp

        from ..obs import solver as solver_obs
        from ..reliability import DegradationLadder, probe
        from ..ops.learning.linear import LinearMapper

        carry = [jnp.asarray(c) for c in carry]
        sa_c, sy_c, mu_a, mu_b = sketch_stream_finish(carry, n)
        s, d = int(sa_c.shape[0]), int(sa_c.shape[1])

        def _primal():
            # s ≥ d: stacked ridge lstsq on [SAc; √λ·I]. The dual form is
            # catastrophically unstable here — K = SAc·SAcᵀ is rank ≤ d,
            # so (K+λI)⁻¹·SYc blows up ~‖SYc‖/λ along K's null space and
            # the cancellation under SAcᵀ is exact only in exact
            # arithmetic; float-reorder noise in the carry (sharded or
            # resumed accumulation) amplifies to ~1e-3 in W.
            trace = jnp.sum(sa_c * sa_c)
            lam = self.reg if self.reg and self.reg > 0 else jnp.maximum(
                1e-6 * trace / s, 1e-6
            )
            stacked = jnp.concatenate(
                [sa_c, jnp.sqrt(lam) * jnp.eye(d, dtype=sa_c.dtype)], axis=0
            )
            rhs = jnp.concatenate(
                [sy_c, jnp.zeros((d, sy_c.shape[1]), sy_c.dtype)], axis=0
            )
            w, *_ = jnp.linalg.lstsq(stacked, rhs, rcond=None)
            return w

        def _dual():
            # s < d: the s×s dual is the whole point of the sketch — the
            # d×d primal never materializes; K is full-rank generically.
            k_mat = sa_c @ sa_c.T
            lam = _reg_floor(k_mat, s, self.reg or 0.0)
            duals = jnp.linalg.solve(
                k_mat + lam * jnp.eye(s, dtype=k_mat.dtype), sy_c
            )
            return sa_c.T @ duals

        def _lstsq():
            w, *_ = jnp.linalg.lstsq(sa_c, sy_c, rcond=None)
            return w

        first = ("primal", _primal) if s >= d else ("dual", _dual)
        ladder = DegradationLadder(
            [first, ("lstsq", _lstsq)], label="sketch.finish"
        )

        attempts = iter(range(len(ladder.rungs)))

        def attempt(rung):
            name, fn = rung
            probe("sketch.finish")
            with solver_obs.rung_span("sketch_ls", name, next(attempts)):
                return fn()

        t0 = time.perf_counter()
        w = ladder.run(attempt)
        self._metric_finish(time.perf_counter() - t0)
        model = LinearMapper(w, intercept=mu_b, feature_mean=mu_a)
        if ladder.reduced:
            model.degradation = dict(
                ladder.record, rung=ladder.record["rung"][0],
                first_rung=ladder.record["first_rung"][0],
            )
        return model

    # -------------------------------------------------------- in-core path
    def fit(self, data, labels):
        """Sketch-and-precondition on materialized data: the sketch
        builds a Woodbury preconditioner and block PCG refines on the
        FULL operator, so accuracy is solver-grade while no d×d matrix
        ever exists."""
        import jax
        import jax.numpy as jnp

        from ..ops.learning.linear import LinearMapper
        from ..ops.stats.core import _as_array_dataset

        features = _as_array_dataset(data)
        targets = _as_array_dataset(labels)
        x = jnp.asarray(features.data, jnp.float32)[: features.num_examples]
        y = jnp.asarray(targets.data, jnp.float32)[: targets.num_examples]
        if y.ndim == 1:
            y = y[:, None]
        n, d = int(x.shape[0]), int(x.shape[1])
        mu_a = jnp.mean(x, axis=0)
        mu_b = jnp.mean(y, axis=0)
        xc, yc = x - mu_a, y - mu_b
        s = self._resolve_sketch_size(d)
        iters = (
            self.refine_iters
            if self.refine_iters is not None
            else _refine_iters_default()
        )
        t0 = time.perf_counter()
        w = sketch_precond_lstsq(
            xc, yc, reg=self.reg or 0.0, sketch_size=s,
            variant=self.variant, seed=self.seed, iters=iters,
        )
        self._observe(
            rows=n, wall_s=time.perf_counter() - t0, variant=self.variant,
            s=s, d=d, k=int(y.shape[1]), refine_iters=iters,
        )
        return LinearMapper(w, intercept=mu_b, feature_mean=mu_a)

    # --------------------------------------------------------- observation
    def _observe(self, rows, wall_s, variant, s, d, k, **extra):
        """Profile-store observation (MeasuredKnobRule reads the best
        recorded sketch size back) + the keystone_sketch_* metrics.
        Best effort — observability must never fail a fit."""
        try:
            from ..obs import names as _names
            from ..ops.learning.block import _record_solver_observation

            _record_solver_observation(
                "sketch_ls", rows=rows, d=d, block_size=s, wall_s=wall_s,
                rungs_attempted=1, sketch_size=s, sketch_variant=variant,
                **extra,
            )
            _names.metric(_names.SKETCH_FITS).inc(variant=variant)
            _names.metric(_names.SKETCH_SIZE).set(s)
            _names.metric(_names.SKETCH_STATE_BYTES).set(
                sketch_state_bytes(s, d, k)
            )
        except Exception:  # pragma: no cover
            pass

    def _metric_finish(self, seconds: float) -> None:
        try:
            from ..obs import names as _names

            _names.metric(_names.SKETCH_FINISH_SECONDS).observe(seconds)
        except Exception:  # pragma: no cover
            pass


# -------------------------------------------------- sketch-and-precondition


def sketch_precond_lstsq(
    xc,
    yc,
    reg: float = 0.0,
    sketch_size: Optional[int] = None,
    variant: str = "countsketch",
    seed: int = 0,
    iters: Optional[int] = None,
    block_rows: int = 8192,
):
    """Solve min ‖xc·w − yc‖² + reg‖w‖² by sketch-and-precondition.

    ``xc``/``yc`` are CENTERED (n, d)/(n, k). The sketch of xc (built
    block-by-block — additivity is exact) yields K = (S·xc)(S·xc)ᵀ and
    the Woodbury preconditioner

        M⁻¹v = (v − (S·xc)ᵀ(K+λI)⁻¹(S·xc)v) / λ,

    the exact inverse of the SKETCHED normal operator — when the sketch
    is a subspace embedding, M⁻¹N has condition O(1) and block PCG on
    the full operator N·v = xcᵀ(xc·v) + λv converges in a handful of
    iterations regardless of xc's conditioning (the sketch-to-
    precondition literature's whole point). Returns w (d, k).
    """
    import jax
    import jax.numpy as jnp
    from jax.scipy.linalg import lu_factor, lu_solve, solve_triangular

    xc = jnp.asarray(xc, jnp.float32)
    yc = jnp.asarray(yc, jnp.float32)
    if yc.ndim == 1:
        yc = yc[:, None]
    n, d = int(xc.shape[0]), int(xc.shape[1])
    s = int(sketch_size or default_sketch_size(d))
    iters = _refine_iters_default() if iters is None else int(iters)

    step = sketch_stream_step(variant, int(seed))
    carry = sketch_stream_init(s, d, int(yc.shape[1]))
    for start in range(0, n, block_rows):
        stop = min(start + block_rows, n)
        mask = jnp.arange(start + 1, stop + 1, dtype=jnp.float32)[:, None]
        carry = step(carry, xc[start:stop], yc[start:stop], mask)
    sa = carry[0]  # xc is pre-centered: the raw sketch IS the centered one

    k_mat = sa @ sa.T
    lam = _reg_floor(k_mat, s, reg)

    if s >= d:
        # Blendenpik form: R from QR of [SA; √λ·I] gives RᵀR = SAᵀSA + λI
        # exactly, applied by two triangular solves — stable where the
        # s×s K (rank ≤ d < s) would NaN a float32 Cholesky.
        stacked = jnp.concatenate(
            [sa, jnp.sqrt(lam) * jnp.eye(d, dtype=sa.dtype)], axis=0
        )
        _, rmat = jnp.linalg.qr(stacked)

        def minv(v):
            t = solve_triangular(rmat.T, v, lower=True)
            return solve_triangular(rmat, t, lower=False)

    else:
        # Very-wide regime: K = SA·SAᵀ is generically full-rank (s < d),
        # so the Woodbury identity inverts (SAᵀSA + λI) through one s×s
        # LU factor.
        lu = lu_factor(k_mat + lam * jnp.eye(s, dtype=k_mat.dtype))

        def minv(v):
            return (v - sa.T @ lu_solve(lu, sa @ v)) / lam

    def nmat(v):  # the full (never materialized) normal operator
        return xc.T @ (xc @ v) + lam * v

    tiny = jnp.asarray(1e-30, jnp.float32)
    b = xc.T @ yc
    w = jnp.zeros_like(b)
    r = b  # w0 = 0
    z = minv(r)
    p = z
    rz = jnp.sum(r * z, axis=0)
    for _ in range(max(iters, 0)):
        q = nmat(p)
        alpha = rz / jnp.maximum(jnp.sum(p * q, axis=0), tiny)
        w = w + alpha * p
        r = r - alpha * q
        z = minv(r)
        rz_new = jnp.sum(r * z, axis=0)
        beta = rz_new / jnp.maximum(rz, tiny)
        p = z + beta * p
        rz = rz_new

    def sketch_only():
        # The dual identity on the sketched system alone — coarser than
        # refined PCG but bounded, and never NaN.
        return sa.T @ jnp.linalg.solve(
            k_mat + lam * jnp.eye(s, dtype=k_mat.dtype), carry[1]
        )

    if iters <= 0:
        w = sketch_only()
    else:
        # Divergence guard: when s undersamples the row space (s well
        # below rank(xc)) M⁻¹N is no longer O(1)-conditioned and PCG can
        # run away — float32 overflow shows up as a residual orders of
        # magnitude past ‖b‖, then NaN. The refined answer is only kept
        # when it beats the starting residual.
        r_norm = jnp.linalg.norm(r)
        b_norm = jnp.linalg.norm(b)
        if not bool(jnp.isfinite(r_norm)) or float(r_norm) > float(b_norm):
            w = sketch_only()
    return jax.block_until_ready(w)


# ------------------------------------------------------------ Nyström KRR


def nystrom_krr(x, y, gamma: float, reg: float, landmarks: int, seed: int = 0):
    """Randomized Nyström kernel ridge: m seeded uniform landmarks, solve
    (K_nmᵀK_nm + reg·K_mm)·α = K_nmᵀy — O(n·m + m²) state instead of the
    full O(n²) kernel. Returns (landmark_indices, duals) for a mapper
    that scores via K(x, landmarks)·α (ops/learning/kernel.py gates the
    path on ``KEYSTONE_KERNEL_NYSTROM``)."""
    import jax.numpy as jnp

    from ..ops.learning.kernel import gaussian_kernel_block

    x = jnp.asarray(x, jnp.float32)
    y = np.asarray(y, np.float64)
    if y.ndim == 1:
        y = y[:, None]
    n = int(x.shape[0])
    m = int(min(landmarks, n))
    rng = np.random.default_rng(np.uint64(seed) ^ np.uint64(0xA11CE5))
    idx = np.sort(rng.choice(n, size=m, replace=False))
    xm = x[jnp.asarray(idx)]
    knm = np.asarray(gaussian_kernel_block(x, xm, gamma), np.float64)  # (n, m)
    kmm = np.asarray(gaussian_kernel_block(xm, xm, gamma), np.float64)  # (m, m)
    lam = max(float(reg), 1e-6)
    # min ‖K_nm·α − y‖² + λ·αᵀK_mm·α as a stacked least squares
    # [K_nm; √λ·Lᵀ]·α ≈ [y; 0] with L = chol(K_mm + jitter) — the normal
    # equations K_nmᵀK_nm square κ(K), which in float32 blows up exactly
    # as m→n on a smooth kernel; the stacked form keeps κ(K) itself and
    # the float64 host solve is cheap next to the O(n·m) panel.
    jitter = 1e-10 * max(float(np.trace(kmm)) / m, 1.0)
    lmat = np.linalg.cholesky(kmm + jitter * np.eye(m))
    stacked = np.concatenate([knm, np.sqrt(lam) * lmat.T], axis=0)
    rhs = np.concatenate([y, np.zeros((m, y.shape[1]))], axis=0)
    duals, *_ = np.linalg.lstsq(stacked, rhs, rcond=None)
    return idx, jnp.asarray(duals, jnp.float32)
