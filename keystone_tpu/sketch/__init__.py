"""Randomized-NLA sketch tier (docs/SOLVERS.md).

Stream-compatible row-space sketching operators with O(s·d) state
(``core``) and the solvers built on them (``solvers``): the third rung
of the least-squares ladder for very-wide fits, plus randomized Nyström
for the kernel path. The streamed sketch carry implements the same
additive state contract the Gram family rides (refit/state.py), so
export/merge/``scaled()``/crash-resume/shard-loss salvage all come for
free — the proof of the "solver-agnostic" claim those subsystems make.

Import discipline: this package imports jax lazily (inside functions),
so control-plane code can import it without paying a backend init.
"""

from .core import (
    MASK_INDEX_EXACT_ROWS,
    sketch_state_bytes,
    sketch_stream_finish,
    sketch_stream_init,
    sketch_stream_step,
)
from .solvers import (
    SketchedLeastSquaresEstimator,
    default_sketch_size,
    nystrom_krr,
    sketch_min_width,
    sketch_precond_lstsq,
)

__all__ = [
    "MASK_INDEX_EXACT_ROWS",
    "SketchedLeastSquaresEstimator",
    "default_sketch_size",
    "nystrom_krr",
    "sketch_min_width",
    "sketch_precond_lstsq",
    "sketch_state_bytes",
    "sketch_stream_finish",
    "sketch_stream_init",
    "sketch_stream_step",
]
