"""keystone-lint rules: the house invariants, as ``ast`` checks.

The plan-time verifier (workflow/verify.py) covers what flows through a
graph; these rules cover what flows through a *diff* — the recurring
review comments that keep the runtime layers honest, encoded so they
fail in CI instead of in review:

========  ============================================================
code      invariant
========  ============================================================
KV501     environment knobs are read through ``envknobs`` only — a raw
          ``os.environ`` / ``os.getenv`` read anywhere else is either
          an import-time snapshot (tests can't monkeypatch it) or an
          undocumented knob. Structural pass-throughs (a supervisor
          cloning its env for a child) annotate ``# keystone: allow-env``.
KV502     no host sync (``block_until_ready`` / ``np.asarray`` /
          ``.item()``) on a span-timed hot path unless it is under a
          ``sync``-gated branch (tracing's ``sync_timings`` discipline)
          or annotated ``# keystone: allow-sync`` with the reason.
KV503     every ``keystone_*`` metric-name literal must be declared in
          ``obs/names.py``'s schema — an undeclared name is a series
          dashboards and the docs-sync test never see.
KV504     every fault-injection ``probe("site")`` label must be
          registered in ``reliability/faultinject.py``'s
          ``KNOWN_PROBE_SITES`` — an unregistered site is chaos surface
          nobody can aim a spec at.
KV505     buffer donation (``donate_argnums``/``donate_argnames``) must
          carry a ``# keystone: owns-donated`` annotation asserting the
          donated buffers are owned copies — donating a caller-visible
          array deletes it out from under the caller.
KV506     ``cost_analysis()`` is called only inside ``obs/cost.py`` —
          its return shape differs per backend (None / list / dict with
          missing keys) and an unguarded call site is a latent crash on
          the next backend; the observatory's harvest helpers guard it
          exactly once (docs/OBSERVABILITY.md "Cost observatory").
========  ============================================================

Rules are pure ``ast`` + source-line checks (stdlib only, nothing is
imported from the linted tree, so linting broken code works). Cross-file
context — the metric-name schema, the probe-site registry — is parsed
out of the package's own source by :func:`build_context`.
docs/VERIFICATION.md documents every code; ``keystone-tpu check --lint``
is the CLI; tier-1 CI enforces a clean tree (scripts/check_smoke.sh).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

from ..diagnostics import ERROR, Diagnostic

ALLOW_ENV = "keystone: allow-env"
ALLOW_SYNC = "keystone: allow-sync"
OWNS_DONATED = "keystone: owns-donated"

#: How far above an expression a pragma comment still applies (a short
#: justification comment block directly over the statement).
_PRAGMA_REACH = 3

#: Modules whose whole point is reading the environment (KV501 exempt).
ENV_MODULES = ("envknobs.py",)

#: Span-timed hot paths (KV502 scope): the per-node execute path, the
#: streamed per-chunk loop, and the serving request/batch loop. A sync
#: anywhere else is a fit-time/setup cost, not a steady-state stall.
HOT_SYNC_MODULES = (
    os.path.join("workflow", "tracing.py"),
    os.path.join("workflow", "executor.py"),
    os.path.join("workflow", "streaming.py"),
    os.path.join("serving", "server.py"),
    os.path.join("serving", "worker.py"),
)

_SYNC_CALLS = ("block_until_ready", "item", "asarray")

#: What a published metric name looks like: ``keystone_<family>_<what>``
#: — at least two segments after the prefix, never the package's own
#: ``keystone_tpu[.module]`` import strings.
_METRIC_SHAPE = re.compile(r"keystone_[a-z0-9]+(_[a-z0-9]+)+$")

LINT_CODES: Dict[str, str] = {
    "KV501": "raw environment read outside envknobs",
    "KV502": "unguarded host sync on a span-timed hot path",
    "KV503": "metric name not declared in obs/names.py",
    "KV504": "probe site not registered in KNOWN_PROBE_SITES",
    "KV505": "buffer donation without ownership annotation",
    "KV506": "cost_analysis() harvested outside obs/cost.py",
}

#: The one module allowed to call ``cost_analysis()`` (KV506).
COST_ANALYSIS_HOME = os.path.join("obs", "cost.py")


class Finding(Diagnostic):
    """One lint/concurrency finding — the source-located face of the
    shared :class:`keystone_tpu.diagnostics.Diagnostic` (one reporting
    path for verify, lint, and concurrency). Keeps the legacy
    ``Finding(rule, path, line, message)`` signature and JSON shape the
    check CLI/CI contracts were built on; ``rule`` aliases ``code``."""

    def __init__(
        self,
        rule: str,
        path: str,
        line: int,
        message: str,
        severity: str = ERROR,
        details: Optional[Dict[str, object]] = None,
    ):
        super().__init__(
            code=rule,
            severity=severity,
            message=message,
            path=path,
            line=line,
            details=dict(details or {}),
        )

    @property
    def rule(self) -> str:
        return self.code

    def to_json(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "rule": self.code,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }
        if self.details:
            out["details"] = self.details
        return out


@dataclass
class LintContext:
    """Cross-file facts the rules check against. ``None`` disables the
    rule that needs it (a fixture tree has no names.py to parse)."""

    metric_names: Optional[Set[str]] = None
    probe_sites: Optional[Set[str]] = None
    #: package-relative paths for KV501/KV502 scoping; findings still
    #: report the caller's path.
    extra_env_modules: Sequence[str] = field(default_factory=tuple)


def _collect_str_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)
        ):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = stmt.value.value
    return out


def build_context(package_root: str) -> LintContext:
    """Parse the linted package's own registries: metric names out of
    ``obs/names.py`` (every module-level ``keystone_*`` string constant),
    probe sites out of ``reliability/faultinject.py``'s
    ``KNOWN_PROBE_SITES`` frozenset literal."""
    metric_names: Optional[Set[str]] = None
    probe_sites: Optional[Set[str]] = None

    names_py = os.path.join(package_root, "obs", "names.py")
    if os.path.exists(names_py):
        with open(names_py, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=names_py)
        metric_names = {
            value
            for value in _collect_str_constants(tree).values()
            if value.startswith("keystone_")
        }

    fault_py = os.path.join(package_root, "reliability", "faultinject.py")
    if os.path.exists(fault_py):
        with open(fault_py, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=fault_py)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "KNOWN_PROBE_SITES"
                    for t in node.targets
                )
            ):
                probe_sites = {
                    c.value
                    for c in ast.walk(node.value)
                    if isinstance(c, ast.Constant) and isinstance(c.value, str)
                }
    return LintContext(metric_names=metric_names, probe_sites=probe_sites)


# --------------------------------------------------------------------- helpers


def _has_pragma(lines: Sequence[str], node: ast.AST, pragma: str) -> bool:
    """True when ``pragma`` appears on any line of ``node``'s span or in
    the ``_PRAGMA_REACH`` lines directly above it (a justification
    comment block)."""
    start = max(0, node.lineno - 1 - _PRAGMA_REACH)
    end = getattr(node, "end_lineno", node.lineno)
    return any(pragma in line for line in lines[start:end])


def _parents(tree: ast.Module) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            out[child] = parent
    return out


def _docstring_nodes(tree: ast.Module) -> Set[int]:
    """ids of Constant nodes that are docstrings (skipped by KV503)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            body = node.body
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


def _is_env_read(node: ast.AST) -> Optional[ast.AST]:
    """The offending node when ``node`` reads the process environment:
    ``os.environ.get/...``, ``os.environ[...]`` (Load), ``os.getenv``,
    ``x in os.environ``, ``dict(os.environ)``/iteration."""

    def is_os_environ(n: ast.AST) -> bool:
        return (
            isinstance(n, ast.Attribute)
            and n.attr == "environ"
            and isinstance(n.value, ast.Name)
            and n.value.id == "os"
        )

    if isinstance(node, ast.Call):
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "os"
            and func.attr == "getenv"
        ):
            return node
        # os.environ.get(...) / .items() / .keys() / .copy()
        if isinstance(func, ast.Attribute) and is_os_environ(func.value):
            if func.attr in ("__setitem__", "setdefault", "update", "pop"):
                return None  # writes/removals are structural, not knob reads
            return node
        # dict(os.environ), iter(os.environ), sorted(os.environ), ...
        if any(is_os_environ(arg) for arg in node.args):
            return node
    if (
        isinstance(node, ast.Subscript)
        and is_os_environ(node.value)
        and isinstance(node.ctx, ast.Load)
    ):
        return node
    if isinstance(node, ast.Compare) and any(
        is_os_environ(comp) for comp in node.comparators
    ):
        return node
    if isinstance(node, ast.comprehension) and is_os_environ(node.iter):
        return node
    return None


def _under_sync_gate(
    node: ast.AST, parents: Dict[ast.AST, ast.AST], src: str
) -> bool:
    """True when ``node`` sits inside an ``if`` whose test mentions a
    ``sync`` name — the tracing layer's ``if sync: _force(...)``
    discipline — or inside a function whose name spells sync."""
    cursor: Optional[ast.AST] = node
    while cursor is not None:
        if isinstance(cursor, ast.If):
            test_src = ast.get_source_segment(src, cursor.test) or ""
            if "sync" in test_src:
                return True
        if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if "sync" in cursor.name:
                return True
        cursor = parents.get(cursor)
    return False


# ----------------------------------------------------------------------- rules


def _check_env_reads(
    tree: ast.Module, lines: Sequence[str], path: str, ctx: LintContext
) -> Iterable[Finding]:
    basename = os.path.basename(path)
    if basename in ENV_MODULES or path.endswith(tuple(ctx.extra_env_modules)):
        return
    for node in ast.walk(tree):
        hit = _is_env_read(node)
        if hit is None:
            continue
        if _has_pragma(lines, hit, ALLOW_ENV):
            continue
        yield Finding(
            "KV501",
            path,
            hit.lineno,
            "raw environment read — go through keystone_tpu.envknobs "
            "(call-time, monkeypatchable, auditable) or annotate a "
            f"structural pass-through with `# {ALLOW_ENV}`",
        )


def _check_host_sync(
    tree: ast.Module, lines: Sequence[str], path: str, ctx: LintContext
) -> Iterable[Finding]:
    if not path.endswith(HOT_SYNC_MODULES):
        return
    src = "\n".join(lines)
    parents = _parents(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = None
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_CALLS:
            # `.item` only counts as the zero-arg scalar fetch;
            # `asarray` only when it is numpy's.
            if func.attr == "item" and node.args:
                continue
            if func.attr == "asarray" and not (
                isinstance(func.value, ast.Name)
                and func.value.id in ("np", "numpy")
            ):
                continue
            name = func.attr
        elif isinstance(func, ast.Name) and func.id in _SYNC_CALLS:
            name = func.id
        if name is None:
            continue
        if _under_sync_gate(node, parents, src):
            continue
        if _has_pragma(lines, node, ALLOW_SYNC):
            continue
        yield Finding(
            "KV502",
            path,
            node.lineno,
            f"`{name}` forces a host sync on a span-timed hot path — "
            "gate it behind the session's sync_timings (workflow/"
            f"tracing.py) or annotate the reason with `# {ALLOW_SYNC}`",
        )


def _check_metric_names(
    tree: ast.Module, lines: Sequence[str], path: str, ctx: LintContext
) -> Iterable[Finding]:
    if ctx.metric_names is None or path.endswith(
        os.path.join("obs", "names.py")
    ):
        return
    docstrings = _docstring_nodes(tree)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and _METRIC_SHAPE.fullmatch(node.value)
            and not node.value.startswith("keystone_tpu")
            and id(node) not in docstrings
            and node.value not in ctx.metric_names
        ):
            yield Finding(
                "KV503",
                path,
                node.lineno,
                f"metric name {node.value!r} is not declared in "
                "obs/names.py's schema — declare it there (and in "
                "docs/OBSERVABILITY.md; the docs-sync test enforces the "
                "pair) before publishing",
            )


def _check_probe_sites(
    tree: ast.Module, lines: Sequence[str], path: str, ctx: LintContext
) -> Iterable[Finding]:
    if ctx.probe_sites is None or path.endswith("faultinject.py"):
        return
    constants = _collect_str_constants(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = (
            func.id
            if isinstance(func, ast.Name)
            else func.attr if isinstance(func, ast.Attribute) else None
        )
        if fname not in ("probe", "wrap") or not node.args:
            continue
        label_node = node.args[0]
        if isinstance(label_node, ast.Constant) and isinstance(
            label_node.value, str
        ):
            label = label_node.value
        elif isinstance(label_node, ast.Name):
            label = constants.get(label_node.id)
        else:
            continue
        if label is None or label in ctx.probe_sites:
            continue
        yield Finding(
            "KV504",
            path,
            node.lineno,
            f"probe site {label!r} is not registered in reliability/"
            "faultinject.py KNOWN_PROBE_SITES — register it so chaos "
            "specs and the failure suite can target it",
        )


def _check_donation(
    tree: ast.Module, lines: Sequence[str], path: str, ctx: LintContext
) -> Iterable[Finding]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg not in ("donate_argnums", "donate_argnames"):
                continue
            # An unconditionally-empty donation tuple donates nothing.
            if isinstance(kw.value, ast.Tuple) and not kw.value.elts:
                continue
            if _has_pragma(lines, node, OWNS_DONATED):
                continue
            yield Finding(
                "KV505",
                path,
                kw.value.lineno,
                f"`{kw.arg}` donates buffers XLA will delete — annotate "
                f"`# {OWNS_DONATED}` on the jit site stating why every "
                "donated argument is an owned copy (tests/ops/"
                "test_donation.py patterns), or drop the donation",
            )


def _check_cost_analysis(
    tree: ast.Module, lines: Sequence[str], path: str, ctx: LintContext
) -> Iterable[Finding]:
    if path.endswith(COST_ANALYSIS_HOME):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = (
            func.attr if isinstance(func, ast.Attribute)
            else func.id if isinstance(func, ast.Name) else None
        )
        if name != "cost_analysis":
            continue
        yield Finding(
            "KV506",
            path,
            node.lineno,
            "`cost_analysis()` called outside obs/cost.py — its return "
            "shape differs per backend (None / list / partial dict); go "
            "through obs.cost.harvest_cost_facts so the guarding and the "
            "zero-extra-compiles invariant live exactly once",
        )


RULES = (
    _check_env_reads,
    _check_host_sync,
    _check_metric_names,
    _check_probe_sites,
    _check_donation,
    _check_cost_analysis,
)


# ---------------------------------------------------------------------- driver


def lint_source(
    source: str,
    path: str = "<string>",
    context: Optional[LintContext] = None,
) -> List[Finding]:
    """Lint one module's source. ``path`` scopes the path-sensitive
    rules (KV501 exemptions, KV502 hot modules)."""
    ctx = context if context is not None else LintContext()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                "KV500",
                path,
                e.lineno or 1,
                f"syntax error: {e.msg} (unparseable files cannot be linted)",
            )
        ]
    lines = source.splitlines()
    findings: List[Finding] = []
    for rule in RULES:
        findings.extend(rule(tree, lines, path, ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _find_package_root(paths: Sequence[str]) -> Optional[str]:
    """The keystone_tpu package root containing/above ``paths``, for
    registry parsing."""
    for path in paths:
        probe = os.path.abspath(path)
        if os.path.isfile(probe):
            probe = os.path.dirname(probe)
        while probe and probe != os.path.dirname(probe):
            if os.path.exists(os.path.join(probe, "obs", "names.py")):
                return probe
            probe = os.path.dirname(probe)
    return None


def lint_paths(
    paths: Sequence[str], context: Optional[LintContext] = None
) -> List[Finding]:
    """Lint files/trees. Builds cross-file context from the enclosing
    package when not given; publishes per-rule finding counters."""
    if context is None:
        root = _find_package_root(paths)
        context = build_context(root) if root else LintContext()
    findings: List[Finding] = []
    for fpath in _iter_py_files(paths):
        with open(fpath, "r", encoding="utf-8") as fh:
            source = fh.read()
        findings.extend(lint_source(source, path=fpath, context=context))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    try:  # metrics are best-effort: linting must work without obs
        from ..obs import names as _names

        counter = _names.metric(_names.VERIFY_LINT_FINDINGS)
        for finding in findings:
            counter.inc(rule=finding.rule)
    except Exception:
        pass
    return findings
