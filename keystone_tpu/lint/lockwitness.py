"""Lock witness: runtime cross-check of the static lock-order graph.

The static model (:mod:`.lockmodel`) claims to know every
acquired-while-holding edge the runtime can take. This module is the
counter-party that keeps it honest: an opt-in instrumented-lock wrapper
(``KEYSTONE_LOCK_WITNESS=1`` at test time) records the acquisition
orders threads ACTUALLY take, names each lock by matching its allocation
site against the static model's table, and fails the run when an
observed edge between two model-known locks is absent from the static
graph — so the model and the runtime cannot silently drift apart
(docs/VERIFICATION.md). The committed baseline
(``lint/lockorder_baseline.json``) records the edges the threaded tier-1
suites actually exercise; ``tests/lint/test_lockwitness.py`` pins
baseline ⊆ static graph.

Mechanics: :func:`lock_witness` patches ``threading.Lock``/``RLock``
(and, through them, default-lock ``Condition``\\ s) with wrappers around
the real primitives. Before each acquisition the wrapper records one
edge per lock currently held by the thread; a reentrant re-acquisition
records nothing (that is what RLocks are for). ``Condition`` wrapping a
witnessed lock delegates acquire/release to the wrapper, so condition
entry/exit and post-``wait`` re-acquisition are all witnessed. Locks
created before installation (module-level registries) are not wrapped —
the witness covers what the test constructs, which is exactly what the
threaded suites exercise.

This module is excluded from the concurrency *model*
(``lockmodel.EXCLUDED_SUFFIXES``): it is the instrument, and modeling
its own wrapper plumbing would only report on itself.
"""

from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..envknobs import env_str

_THIS_FILE = os.path.abspath(__file__)


def witness_enabled() -> bool:
    """``KEYSTONE_LOCK_WITNESS``: any truthy value enables the test
    fixture (``record`` records without asserting; anything else truthy
    — ``1``/``check`` — records AND asserts)."""
    return witness_mode() != "off"


def witness_mode() -> str:
    raw = env_str("KEYSTONE_LOCK_WITNESS", "").lower()
    if raw in ("", "0", "off", "false", "none"):
        return "off"
    return "record" if raw == "record" else "check"


def default_site_names() -> Dict[Tuple[str, int], str]:
    """The installed package's allocation-site → lock-name table, from
    the static model (the same table ``check --concurrency`` builds)."""
    from .lockmodel import build_model

    package_root = os.path.dirname(os.path.dirname(_THIS_FILE))
    return build_model([package_root]).alloc_sites()


class _WitnessLock:
    """One wrapped lock. Delegates everything it doesn't instrument to
    the real primitive (``Condition`` probes ``_is_owned`` etc.)."""

    def __init__(self, witness: "LockWitness", inner, name: str, known: bool):
        self._witness = witness
        self._inner = inner
        self.name = name
        self.known = known

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._witness._before_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness._held().append(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        held = self._witness._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<WitnessLock {self.name}>"


class LockWitness:
    """Collector: per-thread held stacks + the observed edge multiset."""

    def __init__(self, site_names: Optional[Dict[Tuple[str, int], str]] = None):
        self.site_names = dict(site_names or {})
        self._tls = threading.local()
        self._mutex = threading.Lock()  # allocated pre-patch: a real lock
        self._edges: Dict[Tuple[str, str], int] = {}
        self.created = 0  # instrumentation-is-live signal for tests

    # ------------------------------------------------------------- plumbing
    def _held(self) -> List[_WitnessLock]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    def _before_acquire(self, lock: _WitnessLock) -> None:
        held = self._held()
        if any(h is lock for h in held):
            return  # reentrant re-acquisition: no ordering information
        for holder in held:
            if holder is lock:
                continue
            key = (holder.name, lock.name)
            with self._mutex:
                self._edges[key] = self._edges.get(key, 0) + 1

    def _creation_site(self) -> Tuple[str, int]:
        frame = sys._getframe(2)
        while frame is not None:
            filename = frame.f_code.co_filename
            if (
                os.path.abspath(filename) != _THIS_FILE
                and "threading" != os.path.splitext(os.path.basename(filename))[0]
            ):
                return filename, frame.f_lineno
            frame = frame.f_back
        return "<unknown>", 0

    def _name_for(self, filename: str, line: int) -> Tuple[str, bool]:
        normalized = filename.replace(os.sep, "/")
        marker = "keystone_tpu/"
        idx = normalized.rfind(marker)
        if idx >= 0:
            rel = normalized[idx + len(marker):].replace("/", os.sep)
            name = self.site_names.get((rel, line))
            if name is not None:
                return name, True
        tail = "/".join(normalized.split("/")[-2:])
        return f"{tail}:{line}", False

    def _make(self, factory, kind: str) -> _WitnessLock:
        filename, line = self._creation_site()
        name, known = self._name_for(filename, line)
        with self._mutex:
            self.created += 1
        return _WitnessLock(self, factory(), name, known)

    # -------------------------------------------------------------- results
    def observed_edges(self) -> Dict[Tuple[str, str], int]:
        with self._mutex:
            return dict(self._edges)

    def unknown_edges(
        self, static_edges: Set[Tuple[str, str]]
    ) -> List[Tuple[str, str]]:
        """Observed edges between two MODEL-KNOWN locks that the static
        graph does not contain — the drift the witness exists to catch.
        Edges touching locks the model has no name for (test fixtures,
        third-party code) are recorded but never fail the check, and a
        holder the model marked open-world (``holder → <callback>``: it
        is held across a stored callable the model cannot see inside)
        anticipates every outgoing edge."""
        from .lockmodel import CALLBACK

        known_names = {name for _site, name in self.site_names.items()}
        open_world = {a for (a, b) in static_edges if b == CALLBACK}
        out = []
        for (a, b) in sorted(self.observed_edges()):
            if a in open_world:
                continue
            if a in known_names and b in known_names and (a, b) not in static_edges:
                out.append((a, b))
        return out


@contextmanager
def lock_witness(
    site_names: Optional[Dict[Tuple[str, int], str]] = None,
) -> Iterator[LockWitness]:
    """Install the witness: locks created inside the block are wrapped
    and their acquisition orders recorded. ``site_names`` defaults to the
    installed package's static table (:func:`default_site_names`)."""
    witness = LockWitness(
        site_names if site_names is not None else default_site_names()
    )
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    threading.Lock = lambda: witness._make(orig_lock, "lock")  # type: ignore[misc]
    threading.RLock = lambda: witness._make(orig_rlock, "rlock")  # type: ignore[misc]
    try:
        yield witness
    finally:
        threading.Lock = orig_lock  # type: ignore[misc]
        threading.RLock = orig_rlock  # type: ignore[misc]
