"""Static lock model: who holds what, while doing what.

The fact-extraction half of the concurrency tier (the rules half is
:mod:`keystone_tpu.lint.concurrency`). Pure stdlib-``ast`` over source
trees — nothing from the analyzed tree is imported, so analyzing broken
or jax-dependent code costs nothing and works everywhere the lint tier
works.

What is extracted, per package:

- **lock declarations** — ``self._x = threading.Lock()/RLock()/
  Condition()/Semaphore()`` in methods, class-body locks, and
  module-level locks. ``Condition(self._lock)`` aliases the wrapped
  lock (entering the condition IS entering the lock). Every lock gets a
  stable node name (``serving.batcher.MicroBatcher._lock``) and an
  allocation site — the witness (:mod:`.lockwitness`) names runtime
  locks by matching these sites.
- **a lite type environment** — parameter/return annotations,
  ``self.x = ClassName(...)`` constructor assignments, module-level
  singletons plus their accessor functions, ``Dict[...]``/``List[...]``
  element types for loop variables, and base-class joins for functions
  whose returns diverge (``names.metric`` → ``Metric``). Unresolvable
  expressions stay unresolved: the model under-approximates, it never
  guesses.
- **function summaries** — a lexical walk of every function tracking
  the currently-held lock set: lock acquisitions (and so
  acquired-while-holding edges), calls made per held-set, blocking
  calls under a lock, ``self._*`` attribute reads/writes with the held
  set at each site, thread spawns, and future-settling calls.
  Methods named ``*_locked`` are re-walked with the intersection of
  their callers' held sets seeded (the house convention: the caller
  holds the guard).
- **the lock-order graph** — a fixpoint over call summaries resolves
  transitive acquisitions, so ``A.f`` holding ``A._lock`` and calling
  ``B.g`` which takes ``B._lock`` yields the edge
  ``A._lock → B._lock`` even across modules. Cycles in this graph are
  the KV602 deadlock candidates.

The model deliberately ignores semaphores for ordering (counting, not
mutual exclusion) and records an explicit ``.acquire()`` as an edge
source but not a scope (its release is untrackable lexically).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

LOCK_FACTORIES = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Semaphore": "semaphore",
    "BoundedSemaphore": "semaphore",
}

#: Mutating container-method names that count as writes to the attribute.
MUTATOR_METHODS = frozenset(
    {
        "append", "appendleft", "extend", "extendleft", "add", "update",
        "insert", "remove", "discard", "pop", "popleft", "popitem",
        "clear", "setdefault", "sort", "reverse",
    }
)

#: Receiver-name hints that a ``.join()`` is a thread/process join, not
#: a string join.
_JOIN_HINTS = ("thread", "proc", "worker", "monitor")

#: Distinguished graph node: a STORED CALLABLE invoked while a lock is
#: held (``self._thunk()`` in Expression.get, the batcher's
#: ``_on_expired`` callback). The model cannot see inside it, so the
#: holding lock is declared open-world: the edge ``holder → <callback>``
#: lands in the graph, cycle detection ignores the node (it has no
#: outgoing edges), and the lock witness accepts any runtime edge out of
#: such a holder instead of reporting model drift.
CALLBACK = "<callback>"

import builtins as _builtins

_BUILTIN_NAMES = frozenset(dir(_builtins))


# ----------------------------------------------------------------- datatypes


@dataclass(frozen=True)
class LockDecl:
    """One declared lock: its stable node name and allocation site."""

    name: str          # e.g. "serving.batcher.MicroBatcher._lock"
    cls: Optional[str]  # defining class simple name (None: module-level)
    attr: str          # attribute / module variable name
    path: str          # path as given to the analyzer
    relpath: str       # package-relative path ("serving/batcher.py")
    line: int          # allocation line (the witness keys on this)
    kind: str          # lock | rlock | condition | semaphore


@dataclass
class Access:
    """One ``self._attr`` access inside a method."""

    cls: str
    attr: str
    path: str
    line: int
    func: str                  # qualname "Class.method"
    write: bool
    held: FrozenSet[str]       # lock node names held at the access
    thread_reachable: bool = False


@dataclass
class EdgeSite:
    holder: str
    acquired: str
    path: str
    line: int
    func: str
    via: str = ""              # callee chain for indirect edges


@dataclass
class BlockSite:
    path: str
    line: int
    func: str
    call: str                  # rendered call, e.g. "time.sleep"
    held: FrozenSet[str]
    kind: str                  # sleep | result | join | wait | subprocess | socket | semaphore


@dataclass
class ThreadSite:
    path: str
    line: int
    func: str
    daemon: Optional[bool]     # True/False when a constant, None when absent/dynamic
    bound_to: Optional[str]    # dotted binding ("self._monitor"), None when anonymous
    target: Optional[str]      # resolved target qualname when known


@dataclass
class SettleSite:
    path: str
    line: int
    func: str
    method: str                # set_result | set_exception


@dataclass
class _ClassInfo:
    name: str
    module: "_ModuleInfo"
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    lock_attrs: Dict[str, LockDecl] = field(default_factory=dict)
    lock_aliases: Dict[str, str] = field(default_factory=dict)  # attr -> attr
    attr_types: Dict[str, "_TypeRef"] = field(default_factory=dict)

    @property
    def qualname(self) -> str:
        return f"{self.module.dotted}.{self.name}"


@dataclass
class _ModuleInfo:
    path: str
    relpath: str
    dotted: str
    tree: ast.Module
    lines: List[str]
    classes: Dict[str, _ClassInfo] = field(default_factory=dict)
    functions: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    module_locks: Dict[str, LockDecl] = field(default_factory=dict)
    module_aliases: Dict[str, str] = field(default_factory=dict)   # name -> dotted tail
    name_imports: Dict[str, str] = field(default_factory=dict)     # name -> imported name
    singletons: Dict[str, "_TypeRef"] = field(default_factory=dict)


@dataclass(frozen=True)
class _TypeRef:
    """A lite type: a program class (by simple name) or a container of one."""

    cls: str
    container: Optional[str] = None  # "dict" | "list" | None


def _is_threading_call(node: ast.AST) -> Optional[str]:
    """The lock kind when ``node`` is ``threading.X(...)`` (or bare
    ``Lock()`` imported from threading is NOT assumed — only the
    attribute form, which is the house idiom)."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "threading"
        and func.attr in LOCK_FACTORIES
    ):
        return LOCK_FACTORIES[func.attr]
    return None


def _dotted(expr: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` / ``self.x`` as a dotted string, else None."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = _dotted(expr.value)
        return f"{base}.{expr.attr}" if base else None
    return None


def _ann_typeref(ann: Optional[ast.AST]) -> Optional[_TypeRef]:
    """Parse an annotation into a lite type ref: ``_Worker``,
    ``"_Worker"``, ``Optional[_Worker]``, ``Dict[str, _Worker]``,
    ``List[_Worker]``."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        text = ann.value.strip().strip("'\"")
        try:
            return _ann_typeref(ast.parse(text, mode="eval").body)
        except SyntaxError:
            return None
    if isinstance(ann, ast.Name):
        return _TypeRef(ann.id)
    if isinstance(ann, ast.Attribute):
        return _TypeRef(ann.attr)
    if isinstance(ann, ast.Subscript):
        head = ann.value
        head_name = head.attr if isinstance(head, ast.Attribute) else (
            head.id if isinstance(head, ast.Name) else None
        )
        args = ann.slice
        elems = list(args.elts) if isinstance(args, ast.Tuple) else [args]
        if head_name in ("Optional",) and elems:
            return _ann_typeref(elems[0])
        if head_name in ("Dict", "dict") and len(elems) == 2:
            inner = _ann_typeref(elems[1])
            return _TypeRef(inner.cls, "dict") if inner else None
        if head_name in ("List", "list", "Sequence", "Iterable", "Tuple", "tuple", "Set", "set", "Deque", "deque") and elems:
            inner = _ann_typeref(elems[0])
            return _TypeRef(inner.cls, "list") if inner else None
    return None


# ------------------------------------------------------------------- pass 1


def _scan_module(path: str, relpath: str, source: str) -> Optional[_ModuleInfo]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    dotted = relpath[:-3].replace(os.sep, ".") if relpath.endswith(".py") else relpath
    mod = _ModuleInfo(
        path=path, relpath=relpath, dotted=dotted, tree=tree,
        lines=source.splitlines(),
    )
    for stmt in tree.body:
        if isinstance(stmt, (ast.Import,)):
            for alias in stmt.names:
                mod.module_aliases[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(stmt, ast.ImportFrom):
            for alias in stmt.names:
                mod.name_imports[alias.asname or alias.name] = alias.name
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(
            stmt.targets[0], ast.Name
        ):
            name = stmt.targets[0].id
            kind = _is_threading_call(stmt.value)
            if kind is not None:
                mod.module_locks[name] = LockDecl(
                    name=f"{dotted}.{name}", cls=None, attr=name,
                    path=path, relpath=relpath, line=stmt.value.lineno, kind=kind,
                )
            elif isinstance(stmt.value, ast.Call) and isinstance(
                stmt.value.func, ast.Name
            ):
                mod.singletons[name] = _TypeRef(stmt.value.func.id)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[stmt.name] = stmt
        elif isinstance(stmt, ast.ClassDef):
            info = _ClassInfo(name=stmt.name, module=mod, node=stmt)
            for base in stmt.bases:
                base_name = _dotted(base)
                if base_name:
                    info.bases.append(base_name.split(".")[-1])
            for item in stmt.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.methods[item.name] = item
                elif isinstance(item, ast.Assign) and len(item.targets) == 1 and isinstance(
                    item.targets[0], ast.Name
                ):
                    kind = _is_threading_call(item.value)
                    if kind is not None:
                        attr = item.targets[0].id
                        info.lock_attrs[attr] = LockDecl(
                            name=f"{dotted}.{stmt.name}.{attr}", cls=stmt.name,
                            attr=attr, path=path, relpath=relpath,
                            line=item.value.lineno, kind=kind,
                        )
            _scan_self_assignments(info)
            mod.classes[stmt.name] = info
    return mod


def _scan_self_assignments(info: _ClassInfo) -> None:
    """Find ``self.x = threading.Lock()`` / ``self.x = ClassName(...)``
    and annotated ``self.x: T`` across every method."""
    dotted = info.module.dotted
    for method in info.methods.values():
        param_env = _param_env(method)
        for node in ast.walk(method):
            target = None
            value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value = node.target, node.value
                ann = _ann_typeref(node.annotation)
                if (
                    ann is not None
                    and isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    info.attr_types.setdefault(target.attr, ann)
            if (
                target is None
                or not isinstance(target, ast.Attribute)
                or not isinstance(target.value, ast.Name)
                or target.value.id != "self"
            ):
                continue
            attr = target.attr
            kind = _is_threading_call(value)
            if kind is not None:
                # Condition(self._y) aliases the wrapped lock.
                call = value
                if (
                    kind == "condition"
                    and call.args
                    and isinstance(call.args[0], ast.Attribute)
                    and isinstance(call.args[0].value, ast.Name)
                    and call.args[0].value.id == "self"
                ):
                    info.lock_aliases[attr] = call.args[0].attr
                elif attr not in info.lock_attrs:
                    info.lock_attrs[attr] = LockDecl(
                        name=f"{dotted}.{info.name}.{attr}", cls=info.name,
                        attr=attr, path=info.module.path,
                        relpath=info.module.relpath, line=value.lineno, kind=kind,
                    )
            elif isinstance(value, ast.Call) and isinstance(value.func, ast.Name):
                info.attr_types.setdefault(attr, _TypeRef(value.func.id))
            elif isinstance(value, ast.Name) and value.id in param_env:
                # self._b = b  (parameter with a usable annotation)
                info.attr_types.setdefault(attr, param_env[value.id])


# ------------------------------------------------------------------ program


class Program:
    """Package-wide view plus the resolution toolkit."""

    def __init__(self, modules: List[_ModuleInfo]):
        self.modules = modules
        self.by_path: Dict[str, _ModuleInfo] = {m.path: m for m in modules}
        self.classes: Dict[str, List[_ClassInfo]] = {}
        self.functions: Dict[str, List[Tuple[_ModuleInfo, ast.FunctionDef]]] = {}
        for mod in modules:
            for cls in mod.classes.values():
                self.classes.setdefault(cls.name, []).append(cls)
            for name, fn in mod.functions.items():
                self.functions.setdefault(name, []).append((mod, fn))
        self.subclasses: Dict[str, List[_ClassInfo]] = {}
        for lst in self.classes.values():
            for cls in lst:
                for base in cls.bases:
                    self.subclasses.setdefault(base, []).append(cls)
        self._return_memo: Dict[Tuple[str, str], Optional[_TypeRef]] = {}

    # ------------------------------------------------------------- lookup
    def class_by_name(self, name: str) -> Optional[_ClassInfo]:
        lst = self.classes.get(name, [])
        return lst[0] if len(lst) == 1 else None

    def mro(self, cls: _ClassInfo) -> List[_ClassInfo]:
        out, seen, queue = [], set(), [cls]
        while queue:
            cur = queue.pop(0)
            if cur.name in seen:
                continue
            seen.add(cur.name)
            out.append(cur)
            for base in cur.bases:
                resolved = self.class_by_name(base)
                if resolved is not None:
                    queue.append(resolved)
        return out

    def lock_attr(self, cls: _ClassInfo, attr: str) -> Optional[LockDecl]:
        for cur in self.mro(cls):
            attr = cur.lock_aliases.get(attr, attr)
            if attr in cur.lock_attrs:
                return cur.lock_attrs[attr]
        return None

    def find_method(
        self, cls: _ClassInfo, name: str
    ) -> List[Tuple[_ClassInfo, ast.FunctionDef]]:
        """The method on ``cls``/its bases, else on its subclasses (the
        base-join case: a value typed ``Metric`` calling ``.inc`` hits
        ``Counter``/``Gauge``; all matches are returned and their effects
        unioned)."""
        for cur in self.mro(cls):
            if name in cur.methods:
                return [(cur, cur.methods[name])]
        out = []
        for sub in self.subclasses.get(cls.name, []):
            if name in sub.methods:
                out.append((sub, sub.methods[name]))
        return out

    def common_base(self, names: Sequence[str]) -> Optional[str]:
        sets = []
        for name in names:
            cls = self.class_by_name(name)
            if cls is None:
                return None
            sets.append([c.name for c in self.mro(cls)])
        first = sets[0]
        for candidate in first:
            if all(candidate in s for s in sets[1:]):
                return candidate
        return None

    def return_type(
        self, mod: _ModuleInfo, fn: ast.FunctionDef, owner: Optional[_ClassInfo]
    ) -> Optional[_TypeRef]:
        key = (mod.path, f"{owner.name + '.' if owner else ''}{fn.name}")
        if key in self._return_memo:
            return self._return_memo[key]
        self._return_memo[key] = None  # recursion guard
        ref = _ann_typeref(fn.returns)
        if ref is None:
            env = _param_env(fn)
            found: List[str] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    r = self.resolve_type(node.value, mod, owner, env)
                    if r is not None and r.container is None:
                        found.append(r.cls)
            if found:
                joined = found[0] if len(set(found)) == 1 else self.common_base(found)
                if joined:
                    ref = _TypeRef(joined)
        if ref is not None and self.class_by_name(ref.cls) is None:
            ref = None
        self._return_memo[key] = ref
        return ref

    def resolve_type(
        self,
        expr: ast.AST,
        mod: _ModuleInfo,
        owner: Optional[_ClassInfo],
        env: Dict[str, _TypeRef],
    ) -> Optional[_TypeRef]:
        """Lite type of ``expr``: a program class, or None."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and owner is not None:
                return _TypeRef(owner.name)
            if expr.id in env:
                return env[expr.id]
            if expr.id in mod.singletons:
                ref = mod.singletons[expr.id]
                return ref if self.class_by_name(ref.cls) else None
            return None
        if isinstance(expr, ast.Attribute):
            base = self.resolve_type(expr.value, mod, owner, env)
            if base is not None and base.container is None:
                cls = self.class_by_name(base.cls)
                if cls is not None:
                    for cur in self.mro(cls):
                        if expr.attr in cur.attr_types:
                            ref = cur.attr_types[expr.attr]
                            return ref if self.class_by_name(ref.cls) else None
            return None
        if isinstance(expr, ast.Call):
            func = expr.func
            if isinstance(func, ast.Name):
                if self.class_by_name(func.id) is not None:
                    return _TypeRef(func.id)
                target = self._module_function(mod, func.id)
                if target is not None:
                    return self.return_type(target[0], target[1], None)
                return None
            if isinstance(func, ast.Attribute):
                for cls_info, method in self.resolve_method_call(
                    func, mod, owner, env
                ):
                    ref = self.return_type(cls_info.module, method, cls_info)
                    if ref is not None:
                        return ref
            return None
        if isinstance(expr, ast.BoolOp):  # `x = given or default()`
            for value in expr.values:
                ref = self.resolve_type(value, mod, owner, env)
                if ref is not None:
                    return ref
        return None

    def _module_function(
        self, mod: _ModuleInfo, name: str
    ) -> Optional[Tuple[_ModuleInfo, ast.FunctionDef]]:
        if name in mod.functions:
            return (mod, mod.functions[name])
        if name in mod.name_imports:
            imported = mod.name_imports[name]
            lst = self.functions.get(imported, [])
            if len(lst) == 1:
                return lst[0]
        return None

    def resolve_method_call(
        self,
        func: ast.Attribute,
        mod: _ModuleInfo,
        owner: Optional[_ClassInfo],
        env: Dict[str, _TypeRef],
    ) -> List[Tuple[_ClassInfo, ast.FunctionDef]]:
        """Callees of ``<expr>.m(...)`` — empty when unresolvable."""
        # module alias: _names.metric(...)
        if isinstance(func.value, ast.Name) and func.value.id in mod.module_aliases:
            pass  # fall through to module-attr resolution below
        value_type = self.resolve_type(func.value, mod, owner, env)
        if value_type is not None and value_type.container is None:
            cls = self.class_by_name(value_type.cls)
            if cls is not None:
                return self.find_method(cls, func.attr)
        # `<module alias>.fn(...)` — from ..obs import names as _names
        if isinstance(func.value, ast.Name):
            alias = func.value.id
            dotted_mod = None
            if alias in mod.module_aliases:
                dotted_mod = mod.module_aliases[alias]
            elif alias in mod.name_imports:
                dotted_mod = mod.name_imports[alias]
            if dotted_mod is not None:
                tail = dotted_mod.split(".")[-1]
                for other in self.modules:
                    if other.dotted == tail or other.dotted.endswith("." + tail):
                        if func.attr in other.functions:
                            fn = other.functions[func.attr]
                            return [(_module_owner(other), fn)]
        return []


def _module_owner(mod: _ModuleInfo) -> _ClassInfo:
    """A pseudo-class standing for a module, so module functions flow
    through the same (class, function) plumbing."""
    owner = getattr(mod, "_pseudo_owner", None)
    if owner is None:
        owner = _ClassInfo(name=f"<module {mod.dotted}>", module=mod, node=None)
        mod._pseudo_owner = owner  # type: ignore[attr-defined]
    return owner


def _nested_defs(fn: ast.FunctionDef) -> List[ast.FunctionDef]:
    """Directly-nested function definitions (closures), without entering
    them — each gets its own facts entry with a fresh held set."""
    out: List[ast.FunctionDef] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(node)
            continue  # its own nested defs belong to IT
        if isinstance(node, ast.ClassDef):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _param_env(fn: ast.FunctionDef) -> Dict[str, _TypeRef]:
    env: Dict[str, _TypeRef] = {}
    args = list(fn.args.posonlyargs) + list(fn.args.args) + list(
        fn.args.kwonlyargs
    )
    for arg in args:
        ref = _ann_typeref(arg.annotation)
        if ref is not None:
            env[arg.arg] = ref
    return env


# ------------------------------------------------------------------- pass 2


@dataclass
class FunctionFacts:
    qualname: str
    mod: _ModuleInfo
    fn: ast.FunctionDef
    owner: Optional[_ClassInfo]
    acquisitions: List[Tuple[str, int]] = field(default_factory=list)
    edges: List[EdgeSite] = field(default_factory=list)
    calls: List[Tuple[FrozenSet[str], str, int]] = field(default_factory=list)
    blocking: List[BlockSite] = field(default_factory=list)
    accesses: List[Access] = field(default_factory=list)
    threads: List[ThreadSite] = field(default_factory=list)
    settles: List[SettleSite] = field(default_factory=list)
    entry_targets: List[str] = field(default_factory=list)  # spawned callees
    join_roots: Set[str] = field(default_factory=set)
    loop_aliases: Dict[str, str] = field(default_factory=dict)  # var -> iterated dotted


class _Walker:
    """Lexical walk of one function with a held-lock stack."""

    def __init__(
        self,
        program: Program,
        mod: _ModuleInfo,
        owner: Optional[_ClassInfo],
        fn: ast.FunctionDef,
        seed_held: Sequence[str] = (),
        qualname: Optional[str] = None,
    ):
        self.p = program
        self.mod = mod
        self.owner = owner
        self.fn = fn
        self.env = _param_env(fn)
        args = fn.args
        self.param_names = {
            a.arg
            for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        }
        if qualname is None:
            qual = f"{owner.name}.{fn.name}" if owner else fn.name
            qualname = f"{mod.dotted}.{qual}"
        self.facts = FunctionFacts(
            qualname=qualname, mod=mod, fn=fn, owner=owner
        )
        self.held: List[str] = list(seed_held)
        self.held_exprs: List[str] = []  # dotted source of held locks

    # ------------------------------------------------------------- helpers
    def resolve_lock(self, expr: ast.AST) -> Optional[LockDecl]:
        if isinstance(expr, ast.Name):
            return self.mod.module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self.p.resolve_type(expr.value, self.mod, self.owner, self.env)
            if base is not None and base.container is None:
                cls = self.p.class_by_name(base.cls)
                if cls is not None:
                    return self.p.lock_attr(cls, expr.attr)
        return None

    def _record_edges(self, acquired: str, line: int, via: str = "") -> None:
        for holder in self.held:
            if holder != acquired:
                self.facts.edges.append(
                    EdgeSite(
                        holder=holder, acquired=acquired, path=self.mod.path,
                        line=line, func=self.facts.qualname, via=via,
                    )
                )
            elif not via:
                # Lexical re-acquisition of a lock already held: a plain
                # Lock self-deadlocks here. Recorded as a self-edge; the
                # rule layer reports it for non-reentrant kinds only.
                self.facts.edges.append(
                    EdgeSite(
                        holder=holder, acquired=acquired, path=self.mod.path,
                        line=line, func=self.facts.qualname, via="self",
                    )
                )

    # ---------------------------------------------------------------- walk
    def walk(self) -> FunctionFacts:
        self._walk_body(self.fn.body)
        return self.facts

    def _walk_body(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self._walk_stmt(stmt)

    def _walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.With):
            self._walk_with(stmt)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested function: NOT walked inline — it runs whenever it is
            # invoked, not where it is defined, so it gets its own facts
            # entry (fresh held set) under `<parent>.<local name>`; see
            # _nested_defs / walk_all.
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._note_loop_alias(stmt)
            self._visit_expr(stmt.iter)
            self._walk_body(stmt.body)
            self._walk_body(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self._visit_expr(stmt.test)
            self._walk_body(stmt.body)
            self._walk_body(stmt.orelse)
            return
        if isinstance(stmt, ast.If):
            self._visit_expr(stmt.test)
            self._walk_body(stmt.body)
            self._walk_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body)
            for handler in stmt.handlers:
                self._walk_body(handler.body)
            self._walk_body(stmt.orelse)
            self._walk_body(stmt.finalbody)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._walk_assign(stmt)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._visit_expr(stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            self._visit_expr(stmt.value)
            return
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Subscript):
                    self._note_access(target.value, write=True)
                    self._visit_expr(target.value)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._visit_expr(child)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(child)

    def _note_loop_alias(self, stmt: ast.For) -> None:
        if isinstance(stmt.target, ast.Name):
            iter_expr = stmt.iter
            # for x in <expr>.values() / <expr>:
            src = None
            if (
                isinstance(iter_expr, ast.Call)
                and isinstance(iter_expr.func, ast.Attribute)
                and iter_expr.func.attr in ("values", "keys", "items")
            ):
                src = iter_expr.func.value
            else:
                src = iter_expr
            dotted = _dotted(src) if src is not None else None
            if dotted:
                self.facts.loop_aliases[stmt.target.id] = dotted
            ref = (
                self.p.resolve_type(src, self.mod, self.owner, self.env)
                if src is not None else None
            )
            if ref is not None and ref.container in ("dict", "list"):
                self.env.setdefault(stmt.target.id, _TypeRef(ref.cls))

    def _walk_with(self, stmt: ast.With) -> None:
        entered: List[Optional[str]] = []
        for item in stmt.items:
            self._visit_expr(item.context_expr, in_with=True)
            decl = self.resolve_lock(item.context_expr)
            if decl is not None and decl.kind in ("lock", "rlock", "condition"):
                self.facts.acquisitions.append((decl.name, stmt.lineno))
                if not (decl.kind == "rlock" and decl.name in self.held):
                    self._record_edges(decl.name, stmt.lineno)
                self.held.append(decl.name)
                self.held_exprs.append(_dotted(item.context_expr) or "")
                entered.append(decl.name)
            else:
                entered.append(None)
        self._walk_body(stmt.body)
        for name in reversed(entered):
            if name is not None:
                self.held.pop()
                self.held_exprs.pop()

    def _walk_assign(self, stmt: ast.stmt) -> None:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            targets, value = [stmt.target], stmt.value
        elif isinstance(stmt, ast.AugAssign):
            # `self.x += 1` is one read-modify-WRITE of the target.
            self._visit_expr(stmt.value)
            if isinstance(stmt.target, ast.Attribute):
                self._note_access(stmt.target, write=True)
            elif isinstance(stmt.target, ast.Subscript):
                self._note_access(stmt.target.value, write=True)
                self._visit_expr(stmt.target.value)
            return
        if value is not None:
            self._visit_expr(value)
            # Local type propagation: x = ClassName(...) / x = f() / x = self.a
            if len(targets) == 1 and isinstance(targets[0], ast.Name) and isinstance(
                stmt, ast.Assign
            ):
                ref = self.p.resolve_type(value, self.mod, self.owner, self.env)
                if ref is not None:
                    self.env[targets[0].id] = ref
                self._note_thread_binding(value, _dotted(targets[0]))
        for target in targets:
            if isinstance(target, ast.Attribute):
                self._note_access(target, write=True)
            elif isinstance(target, ast.Subscript):
                self._note_access(target.value, write=True)
                self._visit_expr(target.value)
            elif isinstance(target, ast.Tuple):
                for elt in target.elts:
                    if isinstance(elt, ast.Attribute):
                        self._note_access(elt, write=True)
            if isinstance(target, ast.Attribute) and value is not None and isinstance(
                stmt, ast.Assign
            ):
                self._note_thread_binding(value, _dotted(target))

    def _note_thread_binding(self, value: ast.expr, bound_to: Optional[str]) -> None:
        """Attach the binding name to a ThreadSite created in ``value``."""
        for site in self.facts.threads:
            if site.bound_to is None and site.line >= value.lineno and site.line <= (
                getattr(value, "end_lineno", value.lineno)
            ):
                site.bound_to = bound_to

    # ----------------------------------------------------------- expression
    def _visit_expr(self, expr: ast.expr, in_with: bool = False) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
                self._note_access(node, write=False)
            elif isinstance(node, ast.Call):
                self._visit_call(node)

    def _note_access(self, node: ast.AST, write: bool) -> None:
        if not (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and self.owner is not None
        ):
            return
        attr = node.attr
        if attr.startswith("__"):
            return
        if self.p.lock_attr(self.owner, attr) is not None:
            return
        if attr in self.owner.lock_aliases:
            return
        self.facts.accesses.append(
            Access(
                cls=self.owner.qualname, attr=attr, path=self.mod.path,
                line=node.lineno, func=self.facts.qualname, write=write,
                held=frozenset(self.held),
            )
        )

    def _visit_call(self, call: ast.Call) -> None:
        func = call.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        rendered = _dotted(func) or (name or "<call>")

        # Mutating container method on a self attr counts as a write.
        if (
            isinstance(func, ast.Attribute)
            and name in MUTATOR_METHODS
        ):
            self._note_access(func.value, write=True)

        # Thread creation.
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "threading"
            and func.attr == "Thread"
        ) or (isinstance(func, ast.Name) and func.id == "Thread"):
            self._note_thread_create(call)

        # Thread-entry registration: submit / add_done_callback.
        if name in ("submit", "add_done_callback") and call.args:
            target = self._resolve_callable_ref(call.args[0])
            if target:
                self.facts.entry_targets.append(target)

        # Future settles.
        if name in ("set_result", "set_exception") and isinstance(
            func, ast.Attribute
        ):
            self.facts.settles.append(
                SettleSite(
                    path=self.mod.path, line=call.lineno,
                    func=self.facts.qualname, method=name,
                )
            )

        # join bookkeeping (KV604).
        if name == "join" and isinstance(func, ast.Attribute):
            root = _dotted(func.value)
            if root:
                self.facts.join_roots.add(root)

        # Blocking calls under a lock (KV603).
        if self.held:
            self._note_blocking(call, func, name, rendered)

        # Explicit lock ops.
        if name in ("acquire", "release") and isinstance(func, ast.Attribute):
            decl = self.resolve_lock(func.value)
            if decl is not None:
                if name == "acquire" and decl.kind in ("lock", "rlock", "condition"):
                    self.facts.acquisitions.append((decl.name, call.lineno))
                    self._record_edges(decl.name, call.lineno)
                elif decl.kind == "semaphore":
                    # A semaphore's acquire AND release take its internal
                    # condition lock momentarily — the witness observes
                    # that as an ordering edge, so the static graph must
                    # carry it too (held → semaphore is always a leaf:
                    # user code never runs under the internal lock).
                    if self.held:
                        self._record_edges(decl.name, call.lineno)
                    if name == "acquire" and self.held:
                        blocking = not (
                            call.args
                            and isinstance(call.args[0], ast.Constant)
                            and call.args[0].value is False
                        )
                        if blocking:
                            self.facts.blocking.append(
                                BlockSite(
                                    path=self.mod.path, line=call.lineno,
                                    func=self.facts.qualname, call=rendered,
                                    held=frozenset(self.held), kind="semaphore",
                                )
                            )

        # Resolvable call → call-graph edge with the current held set.
        callees = self._callee_keys(func)
        for callee_key in callees:
            self.facts.calls.append(
                (frozenset(self.held), callee_key, call.lineno)
            )

        # Stored-callable invocation the model cannot see inside: the
        # holding lock goes open-world (edge → CALLBACK, transitive via
        # the acquisitions fixpoint so callers holding locks inherit it).
        if not callees and self._is_callback_call(func, name):
            self.facts.acquisitions.append((CALLBACK, call.lineno))
            if self.held:
                self._record_edges(CALLBACK, call.lineno)

    def _is_callback_call(self, func: ast.expr, name: Optional[str]) -> bool:
        if isinstance(func, ast.Attribute):
            if name in ("acquire", "release"):
                return False
            ref = self.p.resolve_type(func.value, self.mod, self.owner, self.env)
            if ref is None or ref.container is not None:
                return False
            cls = self.p.class_by_name(ref.cls)
            if cls is None:
                return False
            if self.p.lock_attr(cls, func.attr) is not None:
                return False
            # A known class whose attribute is NOT a method: a stored
            # callable (thunk, clock, on_expired hook).
            return not self.p.find_method(cls, func.attr)
        if isinstance(func, ast.Name):
            if func.id in _BUILTIN_NAMES:
                return False
            if self.p._module_function(self.mod, func.id) is not None:
                return False
            if self.p.class_by_name(func.id) is not None:
                return False
            # A bare parameter invoked as a function: a passed-in callback.
            return func.id in self.param_names
        return False

    def _resolve_callable_ref(self, expr: ast.expr) -> Optional[str]:
        """Qualname of a function/method reference (Thread target,
        executor submit, done callback)."""
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and self.owner is not None:
                for cur in self.p.mro(self.owner):
                    if expr.attr in cur.methods:
                        return f"{cur.module.dotted}.{cur.name}.{expr.attr}"
        if isinstance(expr, ast.Name):
            target = self.p._module_function(self.mod, expr.id)
            if target is not None:
                return f"{target[0].dotted}.{target[1].name}"
            # nested function defined in the enclosing function body
            for node in ast.walk(self.fn):
                if isinstance(node, ast.FunctionDef) and node.name == expr.id:
                    return f"{self.facts.qualname}.<local {expr.id}>"
        return None

    def _note_thread_create(self, call: ast.Call) -> None:
        daemon: Optional[bool] = None
        target: Optional[str] = None
        for kw in call.keywords:
            if kw.arg == "daemon":
                daemon = (
                    kw.value.value
                    if isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, bool)
                    else None
                )
            if kw.arg == "target":
                target = self._resolve_callable_ref(kw.value)
        self.facts.threads.append(
            ThreadSite(
                path=self.mod.path, line=call.lineno, func=self.facts.qualname,
                daemon=daemon, bound_to=None, target=target,
            )
        )
        if target:
            self.facts.entry_targets.append(target)

    _SUBPROCESS_FNS = ("run", "call", "check_call", "check_output")

    def _note_blocking(
        self, call: ast.Call, func: ast.expr, name: Optional[str], rendered: str
    ) -> None:
        kind: Optional[str] = None
        if rendered == "time.sleep" or (
            isinstance(func, ast.Name) and func.id == "sleep"
        ):
            kind = "sleep"
        elif name == "result" and isinstance(func, ast.Attribute):
            kind = "result"
        elif name == "communicate":
            kind = "subprocess"
        elif (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "subprocess"
            and name in self._SUBPROCESS_FNS
        ):
            kind = "subprocess"
        elif name in ("recv", "accept"):
            kind = "socket"
        elif name == "wait" and isinstance(func, ast.Attribute):
            receiver = _dotted(func.value)
            decl = self.resolve_lock(func.value)
            if decl is not None and decl.name in self.held:
                kind = None  # condition.wait on the held lock: the idiom
            elif receiver is not None and receiver in self.held_exprs:
                kind = None
            else:
                kind = "wait"
        elif name == "join" and isinstance(func, ast.Attribute):
            receiver = func.value
            if isinstance(receiver, ast.Constant):
                kind = None  # ''.join
            else:
                ref = self.p.resolve_type(receiver, self.mod, self.owner, self.env)
                dotted = (_dotted(receiver) or "").lower()
                if ref is not None and ref.cls in ("Thread", "Popen"):
                    kind = "join"
                elif any(h in dotted.split(".")[-1] for h in _JOIN_HINTS):
                    kind = "join"
        elif name == "get" and isinstance(func, ast.Attribute):
            dotted = (_dotted(func.value) or "").lower()
            if "queue" in dotted.split(".")[-1]:
                kind = "wait"
        if kind is not None:
            self.facts.blocking.append(
                BlockSite(
                    path=self.mod.path, line=call.lineno,
                    func=self.facts.qualname, call=rendered,
                    held=frozenset(self.held), kind=kind,
                )
            )

    def _callee_keys(self, func: ast.expr) -> List[str]:
        if isinstance(func, ast.Name):
            target = self.p._module_function(self.mod, func.id)
            if target is not None:
                return [f"{target[0].dotted}.{target[1].name}"]
            return []
        if isinstance(func, ast.Attribute):
            out = []
            for cls_info, method in self.p.resolve_method_call(
                func, self.mod, self.owner, self.env
            ):
                if cls_info.node is None:  # module pseudo-owner
                    out.append(f"{cls_info.module.dotted}.{method.name}")
                else:
                    out.append(
                        f"{cls_info.module.dotted}.{cls_info.name}.{method.name}"
                    )
            return out
        return []


# -------------------------------------------------------------------- model


@dataclass
class LockModel:
    """Everything the rule layer (and the witness) needs.

    ``edges`` keeps EVERY site producing a (holder, acquired) pair, not
    just the first — an ``allow-lock-order`` pragma must suppress a pair
    only when every contributing site carries it.
    """

    locks: Dict[str, LockDecl] = field(default_factory=dict)
    edges: Dict[Tuple[str, str], List[EdgeSite]] = field(default_factory=dict)
    accesses: List[Access] = field(default_factory=list)
    blocking: List[BlockSite] = field(default_factory=list)
    threads: List[ThreadSite] = field(default_factory=list)
    settles: List[SettleSite] = field(default_factory=list)
    functions: Dict[str, FunctionFacts] = field(default_factory=dict)
    entry_functions: Set[str] = field(default_factory=set)
    thread_reachable: Set[str] = field(default_factory=set)
    lines: Dict[str, List[str]] = field(default_factory=dict)  # path -> lines

    def alloc_sites(self) -> Dict[Tuple[str, int], str]:
        """(package-relative path, line) → lock node name — the witness's
        naming table."""
        return {(d.relpath, d.line): d.name for d in self.locks.values()}

    def edge_pairs(self) -> Set[Tuple[str, str]]:
        return set(self.edges)

    def first_site(self, pair: Tuple[str, str]) -> Optional[EdgeSite]:
        sites = self.edges.get(pair)
        return sites[0] if sites else None

    def find_cycles(self) -> List[List[str]]:
        """Elementary cycles in the lock-order graph, one per SCC (plus
        self-loops on non-reentrant locks). Paths are closed:
        ``[a, b, a]``."""
        graph: Dict[str, List[str]] = {}
        for a, b in self.edges:
            graph.setdefault(a, []).append(b)
            graph.setdefault(b, [])
        cycles: List[List[str]] = []
        for a, b in sorted(self.edges):
            if a == b:
                decl = self.locks.get(a)
                if decl is None or decl.kind == "lock":
                    cycles.append([a, a])
        for scc in _sccs(graph):
            if len(scc) < 2:
                continue
            cycles.append(_cycle_path(graph, scc))
        return cycles

    def to_json(self) -> Dict[str, Any]:
        return {
            "locks": {
                name: {
                    "path": d.relpath, "line": d.line, "kind": d.kind,
                    "class": d.cls, "attr": d.attr,
                }
                for name, d in sorted(self.locks.items())
            },
            "edges": [
                {
                    "holder": a, "acquired": b,
                    "path": sites[0].path, "line": sites[0].line,
                    "func": sites[0].func, "via": sites[0].via,
                    "sites": len(sites),
                }
                for (a, b), sites in sorted(self.edges.items())
            ],
        }


def _sccs(graph: Dict[str, List[str]]) -> List[List[str]]:
    """Tarjan, iterative."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]
    for root in sorted(graph):
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, i = work[-1]
            if i == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            neighbors = graph.get(node, [])
            while i < len(neighbors):
                succ = neighbors[i]
                i += 1
                if succ not in index:
                    work[-1] = (node, i)
                    work.append((succ, 0))
                    advanced = True
                    break
                if succ in on_stack:
                    low[node] = min(low[node], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    scc.append(member)
                    if member == node:
                        break
                out.append(scc)
    return out


def _cycle_path(graph: Dict[str, List[str]], scc: List[str]) -> List[str]:
    """One concrete closed path inside a multi-node SCC."""
    members = set(scc)
    start = sorted(scc)[0]
    # BFS back to start restricted to the SCC.
    from collections import deque

    queue = deque([[start]])
    seen = {start}
    while queue:
        path = queue.popleft()
        for succ in graph.get(path[-1], []):
            if succ == start and len(path) > 1:
                return path + [start]
            if succ in members and succ not in seen:
                seen.add(succ)
                queue.append(path + [succ])
    # Self-loop inside the SCC as a fallback.
    return [start, start]


# ------------------------------------------------------------------ builder


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _relpath(path: str, roots: Sequence[str]) -> str:
    apath = os.path.abspath(path)
    for root in roots:
        aroot = os.path.abspath(root)
        if apath.startswith(aroot + os.sep):
            return os.path.relpath(apath, aroot)
    return os.path.basename(path)


#: Modules excluded from the model: the witness instruments locks (it IS
#: the runtime half of this analysis), so modeling its wrapper acquire/
#: release plumbing would only produce noise about itself.
EXCLUDED_SUFFIXES = (os.path.join("lint", "lockwitness.py"),)


def build_model(paths: Sequence[str]) -> LockModel:
    """Parse ``paths`` (files or trees) and extract the full lock model."""
    modules: List[_ModuleInfo] = []
    roots = [p for p in paths if os.path.isdir(p)]
    for fpath in _iter_py_files(paths):
        if fpath.endswith(EXCLUDED_SUFFIXES):
            continue
        try:
            with open(fpath, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        mod = _scan_module(fpath, _relpath(fpath, roots), source)
        if mod is not None:
            modules.append(mod)
    return _assemble(modules)


def build_model_from_sources(sources: Dict[str, str]) -> LockModel:
    """Build the model from in-memory ``{relpath: source}`` (rule unit
    tests; unparseable modules are skipped exactly like on disk)."""
    modules = []
    for relpath, source in sources.items():
        mod = _scan_module(relpath, relpath, source)
        if mod is not None:
            modules.append(mod)
    return _assemble(modules)


def _assemble(modules: List[_ModuleInfo]) -> LockModel:
    program = Program(modules)
    model = LockModel()
    for mod in modules:
        model.lines[mod.path] = mod.lines
        for decl in mod.module_locks.values():
            model.locks[decl.name] = decl
        for cls in mod.classes.values():
            for decl in cls.lock_attrs.values():
                model.locks[decl.name] = decl

    # Walk every function; then re-walk *_locked methods with the
    # intersection of their callers' held sets (two passes propagate
    # locked→locked chains).
    def walk_all(seeds: Dict[str, FrozenSet[str]]) -> Dict[str, FunctionFacts]:
        out: Dict[str, FunctionFacts] = {}

        def walk_one(key, mod, owner, fn):
            out[key] = _Walker(
                program, mod, owner, fn, seeds.get(key, ()), qualname=key
            ).walk()
            # Closures run when invoked, not where defined: each nested
            # def gets its own facts entry (fresh held set) so a
            # Thread(target=<closure>) body is analyzed like any other
            # thread entry instead of being invisible.
            for nested in _nested_defs(fn):
                walk_one(f"{key}.<local {nested.name}>", mod, owner, nested)

        for mod in modules:
            for fname, fn in mod.functions.items():
                walk_one(f"{mod.dotted}.{fname}", mod, None, fn)
            for cls in mod.classes.values():
                for mname, method in cls.methods.items():
                    walk_one(f"{mod.dotted}.{cls.name}.{mname}", mod, cls, method)
        return out

    facts = walk_all({})
    for _ in range(2):
        seeds: Dict[str, FrozenSet[str]] = {}
        call_held: Dict[str, List[FrozenSet[str]]] = {}
        for f in facts.values():
            for held, callee, _line in f.calls:
                call_held.setdefault(callee, []).append(held)
        for key, f in facts.items():
            if not f.fn.name.endswith("_locked"):
                continue
            held_sets = call_held.get(key)
            if not held_sets:
                continue
            seeded = frozenset.intersection(*held_sets)
            if seeded:
                seeds[key] = seeded
        if not seeds:
            break
        facts = walk_all(seeds)

    model.functions = facts

    # Fixpoint: transitive may-acquire sets per function.
    may_acquire: Dict[str, Set[str]] = {
        key: {name for name, _ in f.acquisitions} for key, f in facts.items()
    }
    changed = True
    while changed:
        changed = False
        for key, f in facts.items():
            for _held, callee, _line in f.calls:
                callee_set = may_acquire.get(callee)
                if callee_set and not callee_set <= may_acquire[key]:
                    may_acquire[key] |= callee_set
                    changed = True

    # Edges: lexical (already in facts) + call-site held × callee acquires.
    # Every distinct site is kept: pragma suppression must be per-site.
    def add_edge(pair: Tuple[str, str], site: EdgeSite) -> None:
        sites = model.edges.setdefault(pair, [])
        if not any(
            s.path == site.path and s.line == site.line for s in sites
        ):
            sites.append(site)

    for f in facts.values():
        for site in f.edges:
            add_edge((site.holder, site.acquired), site)
        for held, callee, line in f.calls:
            if not held:
                continue
            for acquired in sorted(may_acquire.get(callee, ())):
                for holder in held:
                    if holder == acquired:
                        decl = model.locks.get(holder)
                        if decl is not None and decl.kind != "lock":
                            continue
                    add_edge(
                        (holder, acquired),
                        EdgeSite(
                            holder=holder, acquired=acquired, path=f.mod.path,
                            line=line, func=f.qualname, via=callee,
                        ),
                    )

    # Thread-entry reachability over the call graph.
    entries: Set[str] = set()
    for f in facts.values():
        entries.update(t for t in f.entry_targets if t)
        for site in f.threads:
            if site.target:
                entries.add(site.target)
    # HTTP handler entry points: do_* methods on BaseHTTPRequestHandler
    # subclasses (each request runs on its own server thread).
    for mod in modules:
        for cls in mod.classes.values():
            if any("HTTPRequestHandler" in b or b == "Handler" for b in cls.bases):
                for mname in cls.methods:
                    if mname.startswith("do_"):
                        entries.add(f"{mod.dotted}.{cls.name}.{mname}")
    model.entry_functions = set(entries)
    reachable: Set[str] = set()
    queue = [e for e in entries if e in facts]
    while queue:
        cur = queue.pop()
        if cur in reachable:
            continue
        reachable.add(cur)
        f = facts.get(cur)
        if f is None:
            continue
        for _held, callee, _line in f.calls:
            if callee not in reachable:
                queue.append(callee)
    model.thread_reachable = reachable

    # Flatten per-function facts, stamping reachability onto accesses.
    for key, f in facts.items():
        in_thread = key in reachable
        for access in f.accesses:
            access.thread_reachable = in_thread
            model.accesses.append(access)
        model.blocking.extend(f.blocking)
        model.threads.extend(f.threads)
        model.settles.extend(f.settles)

    return model
