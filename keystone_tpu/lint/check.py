"""``keystone-tpu check`` — the static-tier CLI.

Three halves, composable in one invocation (docs/VERIFICATION.md):

``--lint [PATH ...]``
    Run keystone-lint (lint/rules.py, stdlib ``ast``) over source trees
    (default: the installed ``keystone_tpu`` package). Any finding fails
    the run; tier-1 CI keeps the shipped tree clean
    (scripts/check_smoke.sh).

``--concurrency [PATH ...]``
    Run the concurrency tier (lint/concurrency.py over the
    lint/lockmodel.py lock model): KV6xx findings — unlocked
    majority-guarded writes, lock-order cycles, blocking under a lock,
    thread/future hygiene — plus the full acquired-while-holding lock
    graph in ``--json`` output (the lock-witness baseline is generated
    from it). Stdlib-only and jax-free like ``--lint``; the JSON carries
    ``jax_free`` so CI can assert no backend was paid for a pure static
    pass.

``--pipeline PATH|synthetic``
    Plan-time graph verification (workflow/verify.py) of a saved
    ``FittedPipeline.save`` artifact — or the synthetic serving chain —
    with an optional bound ``--input-spec``. Pure spec propagation:
    the run installs the compile counter and reports ``xla_compiles``
    so CI can assert the whole pass compiled NOTHING. ``--seed-mismatch``
    deliberately mis-sizes the input spec (the CI negative control: a
    verifier that stops flagging a planted KV101 fails the smoke, not a
    user).

Exit code 0 iff zero lint findings and zero error-severity diagnostics
(warnings don't fail — the same contract as ``KEYSTONE_VERIFY=warn``).
"""

from __future__ import annotations

import argparse
import json
from typing import Any, Dict, List, Optional


def add_check_arguments(parser: argparse.ArgumentParser) -> None:
    """Stdlib-only flag wiring — ``keystone-tpu check --help`` must not
    import jax."""
    parser.add_argument(
        "--lint",
        nargs="*",
        metavar="PATH",
        default=None,
        help="lint source trees (no PATH: the keystone_tpu package)",
    )
    parser.add_argument(
        "--concurrency",
        nargs="*",
        metavar="PATH",
        default=None,
        help="concurrency analysis: KV6xx lock-discipline/deadlock-order "
        "findings + the lock-order graph (no PATH: the keystone_tpu package)",
    )
    parser.add_argument(
        "--pipeline",
        metavar="PATH|synthetic",
        default=None,
        help="verify a FittedPipeline.save artifact, or 'synthetic'",
    )
    parser.add_argument(
        "--input-spec",
        metavar="ROWSxCOLS:DTYPE",
        default=None,
        help="bind the pipeline input spec, e.g. 16x64:float32 "
        "(default for synthetic: 16x64:float32)",
    )
    parser.add_argument(
        "--buckets",
        default=None,
        help="comma-separated serving batch buckets the plan will pad onto",
    )
    parser.add_argument(
        "--warmed-buckets",
        default=None,
        help="comma-separated buckets the AOT warmup covers "
        "(utils/aot.warm_buckets); missing buckets are KV301 errors",
    )
    parser.add_argument(
        "--seed-mismatch",
        action="store_true",
        help="deliberately mis-size the input spec (CI negative control)",
    )
    parser.add_argument(
        "--store",
        action="store_true",
        dest="store_report",
        help="report the profile store's provenance: entries by source "
        "(observed vs tune) and the tuner-written keys",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json", help="JSON output"
    )


def _parse_spec(text: str) -> Any:
    """``16x64:float32`` → ShapeDtypeStruct((16, 64), float32)."""
    import jax
    import numpy as np

    shape_part, _, dtype_part = text.partition(":")
    shape = tuple(int(p) for p in shape_part.split("x") if p)
    return jax.ShapeDtypeStruct(shape, np.dtype(dtype_part or "float32"))


def _parse_buckets(text: Optional[str]) -> Optional[List[int]]:
    if not text:
        return None
    return [int(p) for p in text.split(",") if p.strip()]


def check_from_args(args: argparse.Namespace) -> int:
    from . import lint_paths

    out: Dict[str, Any] = {}
    human: List[str] = []
    ok = True

    if (
        args.lint is None
        and args.pipeline is None
        and args.concurrency is None
        and not getattr(args, "store_report", False)
    ):
        print(
            "keystone-tpu check: nothing to do "
            "(pass --lint, --concurrency, --pipeline, and/or --store)"
        )
        return 2

    if getattr(args, "store_report", False):
        # Profile-store provenance (docs/AUTOTUNING.md): which decisions
        # were actively searched (source=tune) vs passively replayed
        # (source=observed). Pure store read — no jax, no device.
        from ..obs import store as _store

        store = _store.get_store()
        if store is None:
            out["store"] = {"enabled": False}
            human.append("store: disabled (KEYSTONE_PROFILE_STORE=off)")
        else:
            from ..obs.store import is_stale

            by_source = store.by_source()
            tuned_keys = sorted(
                {
                    key
                    for key, _shape, m in store.entries(
                        any_env=True, include_stale=True
                    )
                    if m.get("source") == "tune"
                }
            )
            # Drift-marked entries (obs/cost.py sentinel): still stored
            # for post-hoc inspection, no longer replayed by any rule.
            stale_keys = sorted(
                {
                    key
                    for key, _shape, m in store.entries(
                        any_env=True, include_stale=True
                    )
                    if is_stale(m)
                }
            )
            out["store"] = {
                "enabled": True,
                **store.stats(),
                "by_source": by_source,
                "tuned_keys": tuned_keys,
                "stale_keys": stale_keys,
            }
            human.append(
                f"store[{store.path}]: {len(store)} entries, by source "
                f"{by_source or '{}'}, {len(tuned_keys)} tuned keys, "
                f"{len(stale_keys)} stale"
            )
            human += ["  tuned: " + k for k in tuned_keys[:20]]
            human += ["  stale: " + k for k in stale_keys[:20]]

    if args.lint is not None:
        import keystone_tpu

        import os

        paths = list(args.lint) or [os.path.dirname(keystone_tpu.__file__)]
        findings = lint_paths(paths)
        out["lint"] = {
            "paths": paths,
            "findings": [f.to_json() for f in findings],
            "ok": not findings,
        }
        human.append(
            f"lint[{', '.join(paths)}]: {len(findings)} findings"
        )
        human += ["  " + f.render() for f in findings]
        ok = ok and not findings

    if args.concurrency is not None:
        import os
        import sys
        import time

        import keystone_tpu

        from .concurrency import analyze_paths as analyze_concurrency

        paths = list(args.concurrency) or [
            os.path.dirname(keystone_tpu.__file__)
        ]
        t0 = time.perf_counter()
        findings, model = analyze_concurrency(paths)
        seconds = time.perf_counter() - t0
        out["concurrency"] = {
            "paths": paths,
            "findings": [f.to_json() for f in findings],
            "lock_graph": model.to_json(),
            "seconds": round(seconds, 4),
            # Pure static pass: CI asserts no jax backend was imported
            # (the concurrency analog of --pipeline's xla_compiles == 0).
            "jax_free": "jax" not in sys.modules,
            "ok": not findings,
        }
        human.append(
            f"concurrency[{', '.join(paths)}]: {len(findings)} findings, "
            f"{len(model.locks)} locks, {len(model.edges)} order edges, "
            f"{seconds * 1e3:.0f} ms"
        )
        human += ["  " + f.render() for f in findings]
        ok = ok and not findings

    if args.pipeline is not None:
        # The compile counter must go in BEFORE anything traces: the
        # whole point of plan-time verification is zero XLA compiles,
        # and CI asserts the counter stayed at 0 (check_smoke.sh).
        from ..utils.compilation_cache import install_compile_counter

        compile_count = install_compile_counter()
        from ..workflow.verify import verify_pipeline

        if args.pipeline == "synthetic":
            from ..serving.synthetic import synthetic_chain_pipeline

            pipeline = synthetic_chain_pipeline(num_nodes=4, d=64)
            spec_text = args.input_spec or "16x64:float32"
        else:
            from ..workflow.pipeline import FittedPipeline

            pipeline = FittedPipeline.load(args.pipeline).fused()
            spec_text = args.input_spec
        input_spec = _parse_spec(spec_text) if spec_text else None
        if args.seed_mismatch and input_spec is not None:
            import jax

            # Chop the trailing width: every downstream matmul/projection
            # must reject it — the planted KV101.
            shape = tuple(input_spec.shape)
            bad = shape[:-1] + (max(1, shape[-1] - 1),)
            input_spec = jax.ShapeDtypeStruct(bad, input_spec.dtype)
        report = verify_pipeline(
            pipeline,
            input_spec,
            buckets=_parse_buckets(args.buckets),
            warmed_buckets=_parse_buckets(args.warmed_buckets),
            probe_objects=True,
            context=f"check:{args.pipeline}",
        )
        out["pipeline"] = report.to_json()
        out["xla_compiles"] = compile_count()
        human.append(report.render())
        human.append(f"xla_compiles: {compile_count()}")
        ok = ok and report.ok

    out["ok"] = ok
    if args.as_json:
        print(json.dumps(out))
    else:
        print("\n".join(human))
        print("check: OK" if ok else "check: FAILED")
    return 0 if ok else 1
