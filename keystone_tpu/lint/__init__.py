"""keystone-lint: house-invariant checks over the codebase itself.

The repo half of the static tier (the graph half is
workflow/verify.py): stdlib-``ast`` rules encoding the invariants our
runtime layers depend on — call-time env reads, sync-free hot paths,
declared metric names, registered probe sites, annotated buffer
donation (KV5xx, :mod:`.rules`) — plus the concurrency tier (KV6xx,
:mod:`.concurrency` over the :mod:`.lockmodel` lock model): inferred
lock discipline, deadlock-order cycles, blocking-under-lock, and
thread/future hygiene, cross-checked at test time by the instrumented
lock witness (:mod:`.lockwitness`). ``keystone-tpu check --lint
--concurrency`` runs them; tier-1 CI keeps the tree clean. See
docs/VERIFICATION.md.
"""

from .concurrency import (
    ALLOW_BLOCK_UNDER_LOCK,
    ALLOW_LOCK_ORDER,
    ALLOW_SETTLE,
    ALLOW_UNGUARDED,
    ALLOW_UNJOINED,
    CONCURRENCY_CODES,
    analyze_model,
    analyze_paths,
    analyze_sources,
)
from .lockmodel import LockModel, build_model, build_model_from_sources
from .rules import (
    ALLOW_ENV,
    ALLOW_SYNC,
    LINT_CODES,
    OWNS_DONATED,
    Finding,
    LintContext,
    build_context,
    lint_paths,
    lint_source,
)

__all__ = [
    "ALLOW_BLOCK_UNDER_LOCK",
    "ALLOW_ENV",
    "ALLOW_LOCK_ORDER",
    "ALLOW_SETTLE",
    "ALLOW_SYNC",
    "ALLOW_UNGUARDED",
    "ALLOW_UNJOINED",
    "CONCURRENCY_CODES",
    "LINT_CODES",
    "LockModel",
    "OWNS_DONATED",
    "Finding",
    "LintContext",
    "analyze_model",
    "analyze_paths",
    "analyze_sources",
    "build_context",
    "build_model",
    "build_model_from_sources",
    "lint_paths",
    "lint_source",
]
