"""keystone-lint: house-invariant checks over the codebase itself.

The repo half of the static tier (the graph half is
workflow/verify.py): stdlib-``ast`` rules encoding the invariants our
runtime layers depend on — call-time env reads, sync-free hot paths,
declared metric names, registered probe sites, annotated buffer
donation. ``keystone-tpu check --lint`` runs them; tier-1 CI keeps the
tree clean. See docs/VERIFICATION.md.
"""

from .rules import (
    ALLOW_ENV,
    ALLOW_SYNC,
    LINT_CODES,
    OWNS_DONATED,
    Finding,
    LintContext,
    build_context,
    lint_paths,
    lint_source,
)

__all__ = [
    "ALLOW_ENV",
    "ALLOW_SYNC",
    "LINT_CODES",
    "OWNS_DONATED",
    "Finding",
    "LintContext",
    "build_context",
    "lint_paths",
    "lint_source",
]
