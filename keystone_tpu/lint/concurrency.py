"""Concurrency rules: the KV6xx family over the static lock model.

The rules half of the concurrency tier — :mod:`.lockmodel` extracts the
facts (locks, guard statistics, the acquired-while-holding graph, thread
spawns, blocking calls, future settles); this module turns them into
stable, documented findings the same way :mod:`.rules` does for KV5xx.
``keystone-tpu check --concurrency`` is the CLI; tier-1 CI keeps the
shipped tree clean, and the smoke's seeded fixture (a deliberate
lock-order cycle plus an unlocked guarded write) pins that the analyzer
still fires. The dynamic cross-check lives in :mod:`.lockwitness`:
instrumented locks record the acquisition orders tests actually take,
and an observed edge absent from this model's graph fails the run — the
model and the runtime cannot silently drift.

========  ============================================================
code      invariant
========  ============================================================
KV601     an attribute a class guards with a lock in the (strict)
          majority of its accesses must not be MUTATED without that
          lock — the unlocked read-modify-write drops updates the
          moment a second thread exists. Reviewed exceptions annotate
          ``# keystone: allow-unguarded(reason)``.
KV602     the inter-class acquired-while-holding graph must be acyclic
          — a cycle is a potential deadlock; the finding carries the
          exact closed path (mirroring KV401's cycle reporting). A
          non-reentrant lock re-acquired while already held is the
          one-lock cycle. A deliberate edge (e.g. instance-disjoint by
          construction) annotates
          ``# keystone: allow-lock-order(reason)`` at the acquisition
          site, which drops it from cycle detection but NOT from the
          witness graph.
KV603     no blocking wait while holding a lock — ``Future.result``,
          ``queue.get``, thread/process ``join``/``wait``, ``sleep``,
          socket/subprocess waits stall every thread parked on the
          lock. ``Condition.wait`` on the held lock's own condition is
          the idiom, not a finding. Reviewed sites annotate
          ``# keystone: allow-block-under-lock(reason)``.
KV604     a non-daemon thread must be joined (or annotated
          ``# keystone: allow-unjoined(reason)``) — an untracked
          non-daemon thread outlives shutdown and hangs interpreter
          exit.
KV605     futures are settled only through the shared settle-once
          helpers in ``serving/config.py`` (``settle_result`` /
          ``settle_exception``) — a raw ``set_result``/``set_exception``
          races shutdown/requeue paths into InvalidStateError crashes.
          Annotate ``# keystone: allow-settle(reason)`` where a future
          is provably single-owner.
========  ============================================================
"""

from __future__ import annotations

import os
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

from .lockmodel import LockModel, build_model, build_model_from_sources
from .rules import Finding, _has_pragma  # shared pragma reach

ALLOW_UNGUARDED = "keystone: allow-unguarded"
ALLOW_LOCK_ORDER = "keystone: allow-lock-order"
ALLOW_BLOCK_UNDER_LOCK = "keystone: allow-block-under-lock"
ALLOW_UNJOINED = "keystone: allow-unjoined"
ALLOW_SETTLE = "keystone: allow-settle"

#: Future-settling outside this module is KV605 (the helpers live here).
SETTLE_MODULE = os.path.join("serving", "config.py")

CONCURRENCY_CODES: Dict[str, str] = {
    "KV601": "majority-guarded attribute mutated without its lock",
    "KV602": "lock-order cycle (potential deadlock)",
    "KV603": "blocking call while holding a lock",
    "KV604": "non-daemon thread never joined",
    "KV605": "future settled outside the shared settle-once helpers",
}


class _Pragmas:
    """Pragma lookup against the model's per-file source lines."""

    def __init__(self, model: LockModel):
        self._lines = model.lines

    def has(self, path: str, line: int, pragma: str) -> bool:
        lines = self._lines.get(path)
        if lines is None:
            return False

        class _Anchor:
            lineno = line
            end_lineno = line

        return _has_pragma(lines, _Anchor, pragma)


# ----------------------------------------------------------------- KV601


def _check_guarded_writes(model: LockModel, pragmas: _Pragmas) -> List[Finding]:
    findings: List[Finding] = []
    by_attr: Dict[Tuple[str, str], list] = {}
    for access in model.accesses:
        if access.func.rsplit(".", 1)[-1] in ("__init__", "__new__", "__post_init__"):
            continue
        by_attr.setdefault((access.cls, access.attr), []).append(access)
    for (cls, attr), accesses in sorted(by_attr.items()):
        lock_counts: Counter = Counter()
        for access in accesses:
            for lock in access.held:
                lock_counts[lock] += 1
        if not lock_counts:
            continue
        guard, guarded_n = lock_counts.most_common(1)[0]
        total = len(accesses)
        if guarded_n < 2 or guarded_n * 2 <= total:
            continue  # no strict-majority guard inferred
        for access in accesses:
            if not access.write or guard in access.held:
                continue
            if pragmas.has(access.path, access.line, ALLOW_UNGUARDED):
                continue
            thread_note = (
                " on a thread-entry-reachable path"
                if access.thread_reachable else ""
            )
            findings.append(
                Finding(
                    "KV601",
                    access.path,
                    access.line,
                    f"`self.{attr}` is guarded by `{guard}` in "
                    f"{guarded_n}/{total} accesses but mutated here "
                    f"({access.func}){thread_note} without it — an unlocked "
                    "read-modify-write drops updates under concurrency; "
                    f"take the lock or annotate `# {ALLOW_UNGUARDED}(reason)`",
                    details={
                        "class": cls, "attr": attr, "guard": guard,
                        "guarded": guarded_n, "total": total,
                        "thread_reachable": access.thread_reachable,
                        "func": access.func,
                    },
                )
            )
    return findings


# ----------------------------------------------------------------- KV602


def _check_lock_order(model: LockModel, pragmas: _Pragmas) -> List[Finding]:
    # Drop an edge PAIR from cycle detection only when EVERY site that
    # produces it carries the pragma — one annotated site must not hide
    # an unreviewed site elsewhere taking the same order. The witness
    # still compares against the FULL graph, so the runtime stays
    # covered either way.
    pruned_edges = {}
    for pair, sites in model.edges.items():
        keep = [
            s for s in sites
            if not pragmas.has(s.path, s.line, ALLOW_LOCK_ORDER)
        ]
        if keep:
            pruned_edges[pair] = keep
    pruned = LockModel(locks=model.locks, edges=pruned_edges)
    findings: List[Finding] = []
    for cycle in pruned.find_cycles():
        path_text = " -> ".join(cycle)
        sites = []
        for a, b in zip(cycle, cycle[1:]):
            site = pruned.first_site((a, b))
            if site is not None:
                sites.append(
                    f"{os.path.basename(site.path)}:{site.line} "
                    f"({site.func}) holds `{a}` while acquiring `{b}`"
                    + (f" via {site.via}" if site.via and site.via != "self" else "")
                )
        anchor = pruned.first_site((cycle[0], cycle[1]))
        if len(cycle) == 2 and cycle[0] == cycle[1]:
            message = (
                f"non-reentrant lock `{cycle[0]}` may be acquired while "
                f"already held ({'; '.join(sites)}) — this self-deadlocks; "
                "use an RLock or restructure"
            )
        else:
            message = (
                f"lock-order cycle {path_text} — two threads taking these "
                f"locks in opposite orders deadlock ({'; '.join(sites)}); "
                "impose one global order or annotate a provably "
                f"instance-disjoint edge with `# {ALLOW_LOCK_ORDER}(reason)`"
            )
        findings.append(
            Finding(
                "KV602",
                anchor.path if anchor else "<model>",
                anchor.line if anchor else 0,
                message,
                details={"cycle": cycle, "sites": sites},
            )
        )
    return findings


# ----------------------------------------------------------------- KV603


def _check_blocking(model: LockModel, pragmas: _Pragmas) -> List[Finding]:
    findings: List[Finding] = []
    for site in model.blocking:
        if pragmas.has(site.path, site.line, ALLOW_BLOCK_UNDER_LOCK):
            continue
        held = ", ".join(sorted(site.held))
        findings.append(
            Finding(
                "KV603",
                site.path,
                site.line,
                f"`{site.call}` blocks ({site.kind}) while holding "
                f"`{held}` ({site.func}) — every thread parked on the lock "
                "stalls behind this wait; move it outside the critical "
                f"section or annotate `# {ALLOW_BLOCK_UNDER_LOCK}(reason)`",
                details={
                    "call": site.call, "kind": site.kind,
                    "held": sorted(site.held), "func": site.func,
                },
            )
        )
    return findings


# ----------------------------------------------------------------- KV604


def _join_segments(facts) -> set:
    """Joined-name segments visible in one function: direct receivers
    plus the sources of `for t in <src>: t.join()` loops."""
    out = set()
    for root in facts.join_roots:
        out.add(root.split(".")[-1])
        source = facts.loop_aliases.get(root.split(".")[0])
        if source:
            out.add(source.split(".")[-1])
    return out


def _check_thread_hygiene(model: LockModel, pragmas: _Pragmas) -> List[Finding]:
    global_segments = set()
    for facts in model.functions.values():
        global_segments |= _join_segments(facts)
    findings: List[Finding] = []
    for site in model.threads:
        if site.daemon is True:
            continue
        if pragmas.has(site.path, site.line, ALLOW_UNJOINED):
            continue
        bound_seg = site.bound_to.split(".")[-1] if site.bound_to else None
        if bound_seg:
            if "." in (site.bound_to or ""):
                # Attribute binding (self._monitor, worker.reader_thread):
                # any join in the package counts (shutdown paths join far
                # from the spawn site).
                joined = bound_seg in global_segments
            else:
                # Local binding: only a join in the SAME function counts —
                # another function's local `t.join()` says nothing about
                # this thread.
                owner = model.functions.get(site.func)
                joined = owner is not None and bound_seg in _join_segments(owner)
            if joined:
                continue
        what = (
            f"bound to `{site.bound_to}` but never joined"
            if site.bound_to else "anonymous (never joinable)"
        )
        findings.append(
            Finding(
                "KV604",
                site.path,
                site.line,
                f"non-daemon Thread {what} ({site.func}) — it outlives "
                "shutdown and hangs interpreter exit; pass daemon=True, "
                f"join it, or annotate `# {ALLOW_UNJOINED}(reason)`",
                details={
                    "bound_to": site.bound_to, "daemon": site.daemon,
                    "func": site.func,
                },
            )
        )
    return findings


# ----------------------------------------------------------------- KV605


def _check_settles(model: LockModel, pragmas: _Pragmas) -> List[Finding]:
    findings: List[Finding] = []
    for site in model.settles:
        if site.path.endswith(SETTLE_MODULE):
            continue
        if pragmas.has(site.path, site.line, ALLOW_SETTLE):
            continue
        findings.append(
            Finding(
                "KV605",
                site.path,
                site.line,
                f"raw `{site.method}` ({site.func}) — a future can be "
                "settled twice when shutdown/requeue races completion, and "
                "the second settle crashes with InvalidStateError; use "
                "serving/config.py settle_result/settle_exception, or "
                f"annotate `# {ALLOW_SETTLE}(reason)` for a provably "
                "single-owner future",
                details={"method": site.method, "func": site.func},
            )
        )
    return findings


# ------------------------------------------------------------------ driver


_RULES = (
    _check_guarded_writes,
    _check_lock_order,
    _check_blocking,
    _check_thread_hygiene,
    _check_settles,
)


def analyze_model(model: LockModel) -> List[Finding]:
    pragmas = _Pragmas(model)
    findings: List[Finding] = []
    for rule in _RULES:
        findings.extend(rule(model, pragmas))
    findings.sort(key=lambda f: (f.path, f.line or 0, f.code))
    return findings


def analyze_paths(
    paths: Sequence[str], model: Optional[LockModel] = None
) -> Tuple[List[Finding], LockModel]:
    """Analyze files/trees; returns (findings, model) — the model rides
    along for the CLI's lock-graph JSON and the witness baseline."""
    if model is None:
        model = build_model(paths)
    return analyze_model(model), model


def analyze_sources(sources: Dict[str, str]) -> Tuple[List[Finding], LockModel]:
    """In-memory variant for rule unit tests."""
    model = build_model_from_sources(sources)
    return analyze_model(model), model
